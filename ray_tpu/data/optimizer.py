"""Logical-plan optimizer for Data pipelines.

Parity: ``python/ray/data/_internal/logical/optimizers.py`` and the rule set
under ``_internal/logical/rules/`` — the reference rewrites its logical
operator DAG (projection pushdown, operator fusion, zero-copy conversions)
before planning physical execution. Here the plan is already fused eagerly
(a chain of per-block ops inside one ``TaskMapStage``); this pass works on
that op chain:

* **projection algebra** — adjacent declarative column ops (``select`` /
  ``drop`` / ``rename``, plain-data payloads) coalesce, and projections
  commute LEFT past renames, so a chain like ``rename → select`` becomes
  ``select' → rename'`` with the select adjacent to the source;
* **projection pushdown** — a leading ``select`` over column-pruning
  sources (parquet ReadTasks) moves into the read itself: the pruned
  columns never leave the file (``pq.read_table(columns=...)``).

Opaque ops (map/filter/flat_map/map_batches closures) are barriers — the
optimizer never reorders across them, because a closure may read or create
any column.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

_PROJECTIONS = ("select", "drop", "rename")


def _merge_pair(a: Tuple, b: Tuple) -> Optional[List[Tuple]]:
    """Rewrite [a, b] (both projection ops) to an equivalent, smaller or
    more-pushdown-friendly list, or None when no rule applies. Rules only
    fire when they cannot change error behavior (e.g. a select of a column
    the earlier op removed must still raise at execution)."""
    ka, pa = a
    kb, pb = b
    if ka == "select" and kb == "select":
        # merge only when both name the same column set: select(pa)
        # validates EVERY pa column against the block, so collapsing a
        # pb ⊂ pa pair to select(pb) would swallow the KeyError a missing
        # pa-only column must raise at execution
        if set(pb) == set(pa):
            return [("select", list(pb))]
        return None  # differing sets: keep the chain (and its errors)
    if ka == "drop" and kb == "drop":
        return [("drop", list(pa) + [c for c in pb if c not in pa])]
    if ka == "select" and kb == "drop":
        # drop ignores missing columns, so the pair's error behavior is
        # exactly select(pa)'s; merging to select(pa − pb) would skip the
        # missing-column check for a dropped pa column. Only a no-op drop
        # (disjoint from the selection) is eliminable.
        if not (set(pa) & set(pb)):
            return [("select", list(pa))]
        return None
    if ka == "drop" and kb == "select":
        if not (set(pb) & set(pa)):
            return [("select", list(pb))]
        return None  # selecting a dropped column must still raise
    if ka == "rename" and kb == "rename":
        comp = {k: pb.get(v, v) for k, v in pa.items()}
        for k, v in pb.items():
            if k not in pa.values() and k not in comp:
                comp[k] = v
        return [("rename", comp)]
    if ka == "rename" and kb == "select":
        # commute the select left through the rename (pushdown direction):
        # select post-rename names == select their pre-images, then rename
        # only what survives
        inv = {}
        for k, v in pa.items():
            if v in inv:
                return None  # ambiguous rename target; leave untouched
            inv[v] = k
        pre = []
        for c in pb:
            if c in inv:
                pre.append(inv[c])
            elif c in pa:
                # c was renamed AWAY (source, not target): post-rename it
                # does not exist — the select must raise at runtime, so
                # this pair cannot merge
                return None
            else:
                pre.append(c)
        if len(set(pre)) != len(pre):
            return None
        kept = {k: v for k, v in pa.items() if k in pre}
        out: List[Tuple] = [("select", pre)]
        if kept:
            out.append(("rename", kept))
        return out
    if ka == "rename" and kb == "drop":
        inv = {}
        for k, v in pa.items():
            if v in inv:
                return None
            inv[v] = k
        # a dropped name that was renamed AWAY (source-only) matches no
        # post-rename column: dropping it is a no-op — exclude it rather
        # than wrongly dropping the rename's source
        pre = [
            inv.get(c, c)
            for c in pb
            if not (c in pa and c not in inv)
        ]
        kept = {k: v for k, v in pa.items() if k not in pre}
        out = [("drop", pre)]
        if kept:
            out.append(("rename", kept))
        return out
    return None


def optimize_ops(ops: List[Tuple]) -> List[Tuple]:
    """Canonicalize a fused op chain. Terminates: every applied rule either
    shrinks the chain or moves a select/drop strictly left past a rename,
    and opaque ops partition the chain into independently-optimized runs."""
    ops = list(ops)
    for _ in range(len(ops) * len(ops) + 8):  # safety bound, never hit
        for i in range(len(ops) - 1):
            a, b = ops[i], ops[i + 1]
            if a[0] in _PROJECTIONS and b[0] in _PROJECTIONS:
                merged = _merge_pair(a, b)
                if merged is not None and merged != [a, b]:
                    ops[i : i + 2] = merged
                    break
        else:
            return ops
    return ops


def optimize_plan(sources: List, stages: List):
    """Rewrite (sources, stages) before execution: canonicalize every
    task-map op chain, then push a leading select into column-pruning
    ReadTask sources."""
    from ray_tpu.data.streaming_executor import ReadTask, TaskMapStage

    stages = [
        TaskMapStage(optimize_ops(s.ops)) if isinstance(s, TaskMapStage) else s
        for s in stages
    ]
    if (
        stages
        and isinstance(stages[0], TaskMapStage)
        and stages[0].ops
        and stages[0].ops[0][0] == "select"
        and sources
        and all(
            isinstance(r, ReadTask) and r.supports_columns for r in sources
        )
    ):
        cols = list(stages[0].ops[0][1])
        # an existing per-read restriction (read_parquet(columns=...)) must
        # stay authoritative: push only a NARROWING select; a select of a
        # column the read excludes must keep its runtime KeyError
        if all(
            r.columns is None or set(cols) <= set(r.columns) for r in sources
        ):
            sources = [
                ReadTask(r.fn, r.args, columns=cols, supports_columns=True)
                for r in sources
            ]
            rest = stages[0].ops[1:]
            stages = ([TaskMapStage(rest)] if rest else []) + stages[1:]
    return sources, stages
