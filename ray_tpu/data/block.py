"""Blocks: the unit of data movement.

Parity: ``python/ray/data/block.py`` — a Dataset is a list of block refs in
the object store; blocks here are columnar dicts of numpy arrays (the arrow
table role) with zero-copy store reads feeding ``device_put``.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Union

import numpy as np

Row = Dict[str, Any]
Batch = Dict[str, np.ndarray]


def rows_to_block(rows: List[Row]) -> Batch:
    if not rows:
        return {}
    cols: Dict[str, list] = {k: [] for k in rows[0]}
    for r in rows:
        for k in cols:
            cols[k].append(r[k])
    return {k: np.asarray(v) for k, v in cols.items()}


def block_num_rows(block: Batch) -> int:
    if not block:
        return 0
    return len(next(iter(block.values())))


def block_to_rows(block: Batch) -> Iterable[Row]:
    n = block_num_rows(block)
    keys = list(block.keys())
    for i in range(n):
        yield {k: block[k][i] for k in keys}


def slice_block(block: Batch, start: int, end: int) -> Batch:
    return {k: v[start:end] for k, v in block.items()}


def concat_blocks(blocks: List[Batch]) -> Batch:
    blocks = [b for b in blocks if block_num_rows(b) > 0]
    if not blocks:
        return {}
    keys = blocks[0].keys()
    return {k: np.concatenate([b[k] for b in blocks]) for k in keys}


def normalize_block(data: Union[Batch, List[Row]]) -> Batch:
    if isinstance(data, dict):
        return {k: np.asarray(v) for k, v in data.items()}
    if isinstance(data, list):
        return rows_to_block(data)
    raise TypeError(f"cannot interpret {type(data)} as a block")
