"""Dataset constructors.

Parity: ``python/ray/data/read_api.py`` — ``range``, ``from_items``,
``from_numpy``, ``read_parquet``, ``read_csv``, ``read_json``; file reads are
distributed tasks, one per file (the reference's datasource split model).
"""

from __future__ import annotations

import builtins
import glob as globlib
import os
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import rows_to_block
from ray_tpu.data.dataset import Dataset

_DEFAULT_BLOCK_ROWS = 1000


def range(n: int, *, num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    num_blocks = num_blocks or max(1, min(32, n // _DEFAULT_BLOCK_ROWS or 1))
    per = max(1, (n + num_blocks - 1) // num_blocks)
    if n == 0:
        return Dataset([ray_tpu.put({"id": np.arange(0)})])
    refs = []
    for start in builtins.range(0, n, per):
        end = min(start + per, n)
        refs.append(ray_tpu.put({"id": np.arange(start, end)}))
    return Dataset(refs)


def from_items(items: List[Any], *, num_blocks: int = 4) -> Dataset:
    rows = [it if isinstance(it, dict) else {"item": it} for it in items]
    per = max(1, (len(rows) + num_blocks - 1) // num_blocks)
    refs = []
    for i in builtins.range(0, len(rows), per):
        refs.append(ray_tpu.put(rows_to_block(rows[i : i + per])))
    return Dataset(refs)


def from_numpy(arr, *, column: str = "data", num_blocks: int = 4) -> Dataset:
    """Accepts a single ndarray (named ``column``) or a dict of columns."""
    if isinstance(arr, dict):
        n = len(next(iter(arr.values())))
        per = max(1, (n + num_blocks - 1) // num_blocks)
        refs = []
        for i in builtins.range(0, n, per):
            refs.append(ray_tpu.put({k: np.asarray(v)[i : i + per] for k, v in arr.items()}))
        return Dataset(refs)
    per = max(1, (len(arr) + num_blocks - 1) // num_blocks)
    refs = []
    for i in builtins.range(0, len(arr), per):
        refs.append(ray_tpu.put({column: arr[i : i + per]}))
    return Dataset(refs)


def from_pandas(df) -> Dataset:
    block = {c: df[c].to_numpy() for c in df.columns}
    return Dataset([ray_tpu.put(block)])


def _expand_paths(paths, suffix: str) -> List[str]:
    from ray_tpu._private import external_storage as storage

    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if storage.has_scheme(p):
            # scheme'd prefix: expand through the backend's listing first
            # (directories look like existing keys on the file backend);
            # fall back to treating p as one exact key
            listed = [
                u
                for u in storage.list_uri(p.rstrip("/") + "/")
                if u.endswith(suffix)
            ]
            if listed:
                out.extend(listed)
            elif storage.exists(p):
                out.append(p)
        elif os.path.isdir(p):
            out.extend(sorted(globlib.glob(os.path.join(p, f"*{suffix}"))))
        elif "*" in p:
            out.extend(sorted(globlib.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


import contextlib


@contextlib.contextmanager
def _local_copy(path: str):
    """Scheme'd URIs download to a local temp file for the parser (removed
    after the read); plain paths pass through (parity: pyarrow.fs
    resolution in Data reads)."""
    from ray_tpu._private import external_storage as storage

    if not storage.has_scheme(path):
        yield path
        return
    if path.startswith("file://"):
        # already local: no point copying a multi-GB file through memory
        yield storage.resolve(path)[1]
        return
    import tempfile

    data = storage.read_bytes(path)
    if data is None:
        raise FileNotFoundError(path)
    suffix = os.path.splitext(path)[1]
    with tempfile.NamedTemporaryFile(suffix=suffix, delete=False) as tmp:
        tmp.write(data)
        local = tmp.name
    try:
        yield local
    finally:
        try:
            os.unlink(local)
        except OSError:
            pass


@ray_tpu.remote
def _read_parquet_file(path: str, columns=None):
    import pyarrow.parquet as pq

    with _local_copy(path) as local:
        table = pq.read_table(local, columns=columns)
    return {c: table.column(c).to_numpy(zero_copy_only=False) for c in table.column_names}


@ray_tpu.remote
def _read_csv_file(path: str):
    import csv

    with _local_copy(path) as local, open(local, newline="") as fh:
        reader = csv.DictReader(fh)
        rows = list(reader)
    block = rows_to_block(rows)
    # best-effort numeric conversion
    out = {}
    for k, v in block.items():
        try:
            out[k] = v.astype(np.int64)
        except ValueError:
            try:
                out[k] = v.astype(np.float64)
            except ValueError:
                out[k] = v
    return out


@ray_tpu.remote
def _read_json_file(path: str):
    import json

    rows = []
    with _local_copy(path) as local, open(local) as fh:
        first = fh.read(1)
        fh.seek(0)
        if first == "[":
            rows = json.load(fh)
        else:  # jsonl
            rows = [json.loads(line) for line in fh if line.strip()]
    return rows_to_block(rows)


@ray_tpu.remote
def _read_text_file(path: str):
    with _local_copy(path) as local, open(local) as fh:
        lines = [ln.rstrip("\r\n") for ln in fh]
    return {"text": np.array(lines, dtype=object)}


@ray_tpu.remote
def _read_binary_file(path: str):
    with _local_copy(path) as local, open(local, "rb") as fh:
        data = fh.read()
    return {"bytes": np.array([data], dtype=object),
            "path": np.array([path], dtype=object)}


def read_text(paths) -> Dataset:
    """One block per file of ``{"text": line}`` rows (parity: read_text)."""
    return _lazy_read(_read_text_file, _expand_paths(paths, ".txt"))


def read_binary_files(paths) -> Dataset:
    """One row per file: ``{"bytes": ..., "path": ...}``."""
    return _lazy_read(_read_binary_file, _expand_paths(paths, ""))


def from_arrow(table, *, num_blocks: int = 1) -> Dataset:
    """Arrow table(s) → Dataset. Slicing is zero-copy on the Arrow side;
    numeric columns convert to numpy without a copy where the layout
    allows (parity: ``from_arrow``/ArrowBlockAccessor)."""
    tables = table if isinstance(table, (list, tuple)) else [table]
    refs = []
    for t in tables:
        n = t.num_rows
        per = max(1, (n + num_blocks - 1) // num_blocks)
        for start in builtins.range(0, max(n, 1), per):
            sl = t.slice(start, min(per, n - start))
            refs.append(
                ray_tpu.put(
                    {
                        c: sl.column(c).to_numpy(zero_copy_only=False)
                        for c in sl.column_names
                    }
                )
            )
    return Dataset(refs)


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    """Parquet read with column pruning: ``columns`` (or a subsequent
    ``select_columns``, via the logical optimizer's projection pushdown)
    restricts what is decoded from the files."""
    return _lazy_read(
        _read_parquet_file,
        _expand_paths(paths, ".parquet"),
        columns=list(columns) if columns else None,
        supports_columns=True,
    )


def read_csv(paths) -> Dataset:
    return _lazy_read(_read_csv_file, _expand_paths(paths, ".csv"))


def read_json(paths) -> Dataset:
    return _lazy_read(_read_json_file, _expand_paths(paths, ".json"))


def _lazy_read(
    remote_fn,
    paths: List[str],
    columns: Optional[List[str]] = None,
    supports_columns: bool = False,
) -> Dataset:
    """Source blocks as lazy ReadTasks: the streaming executor submits them
    with a bounded window instead of flooding the cluster with one task per
    file up front (parity: the reference's read-op backpressure)."""
    from ray_tpu.data.streaming_executor import ReadTask

    return Dataset(
        [
            ReadTask(
                remote_fn, (p,), columns=columns, supports_columns=supports_columns
            )
            for p in paths
        ]
    )
