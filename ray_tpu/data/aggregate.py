"""Aggregations + grouped data over the exchange shuffle.

Parity: ``python/ray/data/aggregate.py`` (AggregateFn, Count/Sum/Min/Max/
Mean/Std) and the hash/range exchange operators in
``python/ray/data/_internal/planner/exchange/`` (``sort_task_spec.py:1``):
a map stage partitions every block into k slices (hash of the group key, or
range via sampled boundaries for sort), and reduce task j combines slice j
of every block. All stages are framework tasks over blocks in the object
store — the driver never materializes the dataset.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data.block import Batch, block_num_rows, concat_blocks


class AggregateFn:
    """A named aggregation: init/accumulate-block/merge/finalize."""

    def __init__(self, name: str, init, accumulate_block, merge, finalize=None):
        self.name = name
        self.init = init
        self.accumulate_block = accumulate_block
        self.merge = merge
        self.finalize = finalize or (lambda a: a)


def Count():
    return AggregateFn(
        "count",
        init=lambda: 0,
        accumulate_block=lambda a, block: a + block_num_rows(block),
        merge=lambda a, b: a + b,
    )


def Sum(on: str):
    return AggregateFn(
        f"sum({on})",
        init=lambda: 0.0,
        accumulate_block=lambda a, block: a + float(np.sum(block[on])) if block_num_rows(block) else a,
        merge=lambda a, b: a + b,
    )


def Min(on: str):
    return AggregateFn(
        f"min({on})",
        init=lambda: float("inf"),
        accumulate_block=lambda a, block: min(a, float(np.min(block[on]))) if block_num_rows(block) else a,
        merge=min,
    )


def Max(on: str):
    return AggregateFn(
        f"max({on})",
        init=lambda: float("-inf"),
        accumulate_block=lambda a, block: max(a, float(np.max(block[on]))) if block_num_rows(block) else a,
        merge=max,
    )


def Mean(on: str):
    return AggregateFn(
        f"mean({on})",
        init=lambda: (0.0, 0),
        accumulate_block=lambda a, block: (
            a[0] + float(np.sum(block[on])),
            a[1] + block_num_rows(block),
        )
        if block_num_rows(block)
        else a,
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
        finalize=lambda a: a[0] / a[1] if a[1] else float("nan"),
    )


def Std(on: str, ddof: int = 1):
    # Welford-style mergeable (count, mean, M2)
    def acc(a, block):
        n = block_num_rows(block)
        if not n:
            return a
        col = np.asarray(block[on], dtype=np.float64)
        bn, bmean, bm2 = n, float(col.mean()), float(((col - col.mean()) ** 2).sum())
        return _merge_moments(a, (bn, bmean, bm2))

    def _merge_moments(a, b):
        (na, ma, m2a), (nb, mb, m2b) = a, b
        if na == 0:
            return b
        if nb == 0:
            return a
        n = na + nb
        delta = mb - ma
        return (n, ma + delta * nb / n, m2a + m2b + delta * delta * na * nb / n)

    return AggregateFn(
        f"std({on})",
        init=lambda: (0, 0.0, 0.0),
        accumulate_block=acc,
        merge=_merge_moments,
        finalize=lambda a: (a[2] / (a[0] - ddof)) ** 0.5 if a[0] > ddof else float("nan"),
    )


# ---------------------------------------------------------------------------
# exchange tasks
# ---------------------------------------------------------------------------


@ray_tpu.remote
def _hash_partition(block: Batch, key: str, k: int):
    """Map stage of the hash exchange: k slices keyed by hash(key) % k."""
    n = block_num_rows(block)
    if n == 0:
        return [dict() for _ in range(k)] if k > 1 else {}
    col = block[key]
    if col.dtype.kind in "SUO":
        # deterministic across processes (Python's str hash is salted per
        # process, which would scatter equal keys to different partitions)
        import zlib

        idx = np.array([zlib.crc32(str(v).encode()) % k for v in col])
    else:
        idx = np.abs(col.astype(np.int64, copy=False)) % k
    out = []
    for j in range(k):
        mask = idx == j
        out.append({c: v[mask] for c, v in block.items()})
    return out if k > 1 else out[0]


@ray_tpu.remote
def _range_partition(block: Batch, key: str, boundaries):
    """Map stage of the range exchange (sort): len(boundaries)+1 slices."""
    k = len(boundaries) + 1
    if block_num_rows(block) == 0:
        return [dict() for _ in range(k)] if k > 1 else {}
    col = block[key]
    idx = np.searchsorted(np.asarray(boundaries), col, side="right")
    out = []
    for j in range(k):
        mask = idx == j
        out.append({c: v[mask] for c, v in block.items()})
    return out if k > 1 else out[0]


@ray_tpu.remote
def _sort_merge(key: str, descending: bool, *slices: Batch) -> Batch:
    merged = concat_blocks(list(slices))
    if not merged:
        return {}
    order = np.argsort(merged[key], kind="stable")
    if descending:
        order = order[::-1]
    return {c: v[order] for c, v in merged.items()}


@ray_tpu.remote
def _sample_keys(block: Batch, key: str, m: int):
    n = block_num_rows(block)
    if n == 0:
        return np.array([])
    step = max(1, n // m)
    return np.sort(np.asarray(block[key]))[::step][:m]


def _iter_groups(merged: Batch, key: str):
    """Yield (key_value, group_block) over a merged partition, grouped by
    a stable sort on the key column."""
    col = merged[key]
    order = np.argsort(col, kind="stable")
    sorted_block = {c: v[order] for c, v in merged.items()}
    keys_sorted = sorted_block[key]
    uniq, starts = np.unique(keys_sorted, return_index=True)
    bounds = list(starts) + [len(keys_sorted)]
    for gi in range(len(uniq)):
        s, e = bounds[gi], bounds[gi + 1]
        yield uniq[gi], {c: v[s:e] for c, v in sorted_block.items()}


@ray_tpu.remote
def _group_reduce(key: str, agg_blobs, *slices: Batch):
    """Reduce stage of the hash exchange: group rows, apply aggregations."""
    import cloudpickle

    aggs: List[AggregateFn] = [cloudpickle.loads(b) for b in agg_blobs]
    merged = concat_blocks(list(slices))
    if not merged:
        return {}
    out: Dict[str, list] = {key: []}
    for a in aggs:
        out[a.name] = []
    for key_value, group in _iter_groups(merged, key):
        out[key].append(key_value)
        for a in aggs:
            acc = a.accumulate_block(a.init(), group)
            out[a.name].append(a.finalize(acc))
    return {c: np.asarray(v) for c, v in out.items()}


@ray_tpu.remote
def _map_groups_reduce(key: str, fn_blob, *slices: Batch):
    import cloudpickle

    from ray_tpu.data.block import normalize_block

    fn = cloudpickle.loads(fn_blob)
    merged = concat_blocks(list(slices))
    if not merged:
        return {}
    outs = []
    for _, group in _iter_groups(merged, key):
        outs.append(normalize_block(fn(group)))
    return concat_blocks(outs)


@ray_tpu.remote
def _partial_agg(block: Batch, agg_blobs):
    import cloudpickle

    aggs = [cloudpickle.loads(b) for b in agg_blobs]
    return [a.accumulate_block(a.init(), block) for a in aggs]


class GroupedData:
    """Parity: ``ray.data.grouped_data.GroupedData``."""

    def __init__(self, dataset, key: str):
        self._ds = dataset
        self._key = key

    def _exchange(self, reduce_task, payload):
        """Hash exchange: partition every block by key, then reduce each
        partition with ``reduce_task(key, payload, *slices)``."""
        from ray_tpu.data.dataset import Dataset

        mat = self._ds.materialize()
        k = max(1, len(mat._block_refs))
        parts = [
            _hash_partition.options(num_returns=k).remote(ref, self._key, k)
            for ref in mat._block_refs
        ]
        if k == 1:
            parts = [[p] for p in parts]
        out = [
            reduce_task.remote(self._key, payload, *[row[j] for row in parts])
            for j in range(k)
        ]
        return Dataset(out)

    def aggregate(self, *aggs: AggregateFn):
        import cloudpickle

        return self._exchange(_group_reduce, [cloudpickle.dumps(a) for a in aggs])

    def map_groups(self, fn: Callable):
        import cloudpickle

        return self._exchange(_map_groups_reduce, cloudpickle.dumps(fn))

    def count(self):
        return self.aggregate(Count())

    def sum(self, on: str):
        return self.aggregate(Sum(on))

    def min(self, on: str):
        return self.aggregate(Min(on))

    def max(self, on: str):
        return self.aggregate(Max(on))

    def mean(self, on: str):
        return self.aggregate(Mean(on))

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(Std(on, ddof))
