"""Dataset: lazy, distributed, streaming-consumable data.

Parity: ``python/ray/data/dataset.py`` — lazy logical plan → execution over
framework tasks with blocks in the object store; ``map_batches``
(``dataset.py:383``), ``iter_batches`` (``:3668``), ``streaming_split``
(``:1236``). Execution is an operator pipeline driven by the streaming
executor (``ray_tpu/data/streaming_executor.py``): every stage — bounded
read submission, fused task maps, actor pools, rebatching — runs
concurrently over bounded windows, so stage 2 processes block k while
stage 1 is still reading block k+n (the role of the reference's
``StreamingExecutor``, ``streaming_executor.py:48``).
"""

from __future__ import annotations

import builtins
import os
from typing import Any, Callable, Dict, Iterator, List, Optional, Union

import numpy as np

import ray_tpu
from ray_tpu.data.block import (
    Batch,
    block_num_rows,
    block_to_rows,
    concat_blocks,
    normalize_block,
    rows_to_block,
    slice_block,
)

# an operator is (kind, fn) applied block-wise; fused into one task per block
_PREFETCH = 4


def _apply_ops(block: Batch, ops) -> Batch:
    import cloudpickle

    for kind, payload in ops:
        # declarative column ops carry plain data (no closure): they stay
        # inspectable for the logical optimizer (ray_tpu/data/optimizer.py)
        if kind == "select":
            missing = [c for c in payload if c not in block]
            if missing:
                raise KeyError(f"select_columns: missing {missing}")
            block = {k: block[k] for k in payload}
            continue
        if kind == "drop":
            block = {k: v for k, v in block.items() if k not in payload}
            continue
        if kind == "rename":
            block = {payload.get(k, k): v for k, v in block.items()}
            continue
        fn = cloudpickle.loads(payload)
        if kind == "map_batches":
            block = normalize_block(fn(block))
        elif kind == "map":
            block = rows_to_block([fn(r) for r in block_to_rows(block)])
        elif kind == "filter":
            block = rows_to_block([r for r in block_to_rows(block) if fn(r)])
        elif kind == "flat_map":
            out = []
            for r in block_to_rows(block):
                out.extend(fn(r))
            block = rows_to_block(out)
        else:
            raise ValueError(kind)
    return block


@ray_tpu.remote
def _exec_block(block_or_ref, ops):
    block = block_or_ref
    return _apply_ops(block, ops)


class Dataset:
    """A lazy plan: sources (block refs / lazy read tasks) + operator stages
    executed by the streaming executor."""

    def __init__(
        self,
        block_refs: List,
        ops: Optional[List] = None,
        owned_actors=None,
        stages: Optional[List] = None,
    ):
        from ray_tpu.data.streaming_executor import TaskMapStage

        self._block_refs = list(block_refs)
        self._stages: List = list(stages or [])
        if ops:
            self._stages.append(TaskMapStage(ops))
        # actor pools whose pending tasks produce our blocks: pinned here so
        # handle-count reaping can't kill them before the blocks materialize
        self._owned_actors = list(owned_actors or [])

    @property
    def _ops(self) -> Optional[List]:
        """The fused per-block op chain, when the whole plan is one fused
        task-map over materialized refs — the fast path remote helpers
        (_write_block, _block_unique, ...) can apply in a single task.
        None when the plan has other stage kinds or lazy read sources."""
        from ray_tpu.data.streaming_executor import ReadTask, TaskMapStage

        if any(isinstance(r, ReadTask) for r in self._block_refs):
            return None
        ops: List = []
        for stage in self._stages:
            if not isinstance(stage, TaskMapStage):
                return None
            ops.extend(stage.ops)
        return ops

    def _refs_and_ops(self):
        """(source refs, fused ops) — materializing first when the plan is
        not a pure fused task-map chain."""
        ops = self._ops
        if ops is None:
            return self.materialize()._block_refs, []
        return self._block_refs, ops

    # -- transformations (lazy) -------------------------------------------

    def _with_op(self, kind: str, fn: Callable) -> "Dataset":
        import cloudpickle

        return self._with_raw_op((kind, cloudpickle.dumps(fn)))

    def _with_raw_op(self, op) -> "Dataset":
        from ray_tpu.data.streaming_executor import TaskMapStage

        stages = list(self._stages)
        if stages and isinstance(stages[-1], TaskMapStage):
            # fuse into the trailing task-map: the chain runs as ONE task
            # per block (the reference's operator fusion)
            stages[-1] = stages[-1].fused([op])
        else:
            stages.append(TaskMapStage([op]))
        return Dataset(
            self._block_refs, owned_actors=self._owned_actors, stages=stages
        )

    def _with_stage(self, stage) -> "Dataset":
        return Dataset(
            self._block_refs,
            owned_actors=self._owned_actors,
            stages=self._stages + [stage],
        )

    def map(self, fn: Callable) -> "Dataset":
        return self._with_op("map", fn)

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        compute=None,
    ) -> "Dataset":
        # batch_size=None applies fn per block (the common, fastest path);
        # with batch_size the plan gains a streaming rebatch stage first
        from ray_tpu.data.streaming_executor import RebatchStage

        ds = (
            self
            if batch_size is None
            else self._with_stage(RebatchStage(batch_size))
        )
        from ray_tpu.data.context import ActorPoolStrategy

        if isinstance(compute, ActorPoolStrategy):
            return ds._map_batches_actor_pool(fn, compute)
        return ds._with_op("map_batches", fn)

    def _map_batches_actor_pool(self, fn: Callable, strategy) -> "Dataset":
        """Run fn in a pool of long-lived actors (parity:
        ActorPoolMapOperator): callable classes are constructed once per
        actor; plain fns just avoid re-pickling per block. Lazy: the pool
        spins up when the pipeline is consumed, and blocks stream through
        it with a bounded window — upstream stages keep producing while
        the pool works (no plan-time drain barrier)."""
        import cloudpickle

        from ray_tpu.data.streaming_executor import ActorMapStage

        return self._with_stage(
            ActorMapStage(
                cloudpickle.dumps(fn),
                strategy.size,
                max_size=getattr(strategy, "max_size", None),
            )
        )

    def filter(self, fn: Callable) -> "Dataset":
        return self._with_op("filter", fn)

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._with_op("flat_map", fn)

    def union(self, other: "Dataset") -> "Dataset":
        if self._stages or other._stages:
            return Dataset(
                self.materialize()._block_refs + other.materialize()._block_refs,
                owned_actors=self._owned_actors + other._owned_actors,
            )
        return Dataset(
            self._block_refs + other._block_refs,
            owned_actors=self._owned_actors + other._owned_actors,
        )

    def zip(self, other: "Dataset") -> "Dataset":
        """Row-aligned zip: right-side blocks are re-sliced to the left's
        block boundaries (streaming, one block in driver memory at a time)."""
        left = self.materialize()
        right_blocks = other._iter_exec_blocks()
        buf: List[Batch] = []
        buffered = 0
        refs = []
        total_left = 0
        for lref in left._block_refs:
            lb = _fetch(lref)
            n = block_num_rows(lb)
            total_left += n
            while buffered < n:
                try:
                    nb = next(right_blocks)
                except StopIteration:
                    raise ValueError(
                        "zip(): datasets have different row counts"
                    ) from None
                buf.append(nb)
                buffered += block_num_rows(nb)
            merged = concat_blocks(buf)
            rb = slice_block(merged, 0, n)
            buf = [slice_block(merged, n, block_num_rows(merged))]
            buffered -= n
            out = dict(lb)
            for k, v in rb.items():
                out[k if k not in out else f"{k}_1"] = v
            refs.append(ray_tpu.put(out))
        for nb in right_blocks:
            buffered += block_num_rows(nb)
        if buffered:
            raise ValueError("zip(): datasets have different row counts")
        return Dataset(refs)

    # -- column ops (parity: Dataset.add_column/drop_columns/select_columns/
    # rename_columns, python/ray/data/dataset.py) -------------------------

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        """fn receives the whole batch (dict of columns) and returns the new
        column as an array (the reference's batch-wise contract)."""

        def _add(batch):
            out = dict(batch)
            out[name] = np.asarray(fn(batch))
            return out

        return self._with_op("map_batches", _add)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        # declarative (no closure): the logical optimizer coalesces chains
        # of these and pushes projections into column-pruning reads
        return self._with_raw_op(("drop", list(cols)))

    def select_columns(self, cols: List[str]) -> "Dataset":
        return self._with_raw_op(("select", list(cols)))

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        return self._with_raw_op(("rename", dict(mapping)))

    def unique(self, column: str) -> List:
        """Distinct values of one column: per-block remote uniques, only the
        small distinct sets travel to the driver."""
        seen: set = set()
        src_refs, ops = self._refs_and_ops()
        refs = [_block_unique.remote(ref, ops, column) for ref in src_refs]
        for vals in ray_tpu.get(refs, timeout=600):
            seen.update(vals)
        return sorted(seen)

    def random_sample(self, fraction: float, *, seed: Optional[int] = None) -> "Dataset":
        """Bernoulli sample of rows (parity: ``Dataset.random_sample``).

        Seeded per (seed, block index) so a seeded sample is reproducible —
        including across task retries and lineage reconstruction — regardless
        of block content or dtype."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError("fraction must be in [0, 1]")
        base = seed if seed is not None else int.from_bytes(os.urandom(4), "little")
        mat = self.materialize()
        refs = [
            _sample_block.remote(ref, fraction, base, i)
            for i, ref in enumerate(mat._block_refs)
        ]
        return Dataset(refs, owned_actors=mat._owned_actors)

    def take_batch(self, batch_size: int = 20) -> Batch:
        """First batch_size rows as one batch dict (parity: take_batch)."""
        pieces = []
        taken = 0
        for block in self._iter_exec_blocks():
            n = block_num_rows(block)
            take = min(batch_size - taken, n)
            if take:
                pieces.append(slice_block(block, 0, take))
                taken += take
            if taken >= batch_size:
                break
        if not pieces:
            raise ValueError("dataset is empty")
        return concat_blocks(pieces)

    def limit(self, n: int) -> "Dataset":
        out_blocks = []
        taken = 0
        for block in self._iter_exec_blocks():
            rows = block_num_rows(block)
            if taken + rows > n:
                block = slice_block(block, 0, n - taken)
                rows = block_num_rows(block)
            if rows:
                out_blocks.append(ray_tpu.put(block))
                taken += rows
            if taken >= n:
                break
        return Dataset(out_blocks)

    def repartition(self, num_blocks: int) -> "Dataset":
        """Streaming repartition: two passes over materialized blocks (block
        fetches are zero-copy shm maps), one block resident at a time."""
        mat = self.materialize()
        total = sum(block_num_rows(_fetch(r)) for r in mat._block_refs)
        per = max(1, (total + num_blocks - 1) // num_blocks)
        return mat.repartition_by_rows(per)

    def repartition_by_rows(self, rows_per_block: int) -> "Dataset":
        """Re-slice the block stream into fixed-size blocks. Executes the
        rebatch (streaming: prefetch window upstream, one output block
        resident in the driver at a time) so block-count metadata is
        immediately correct; map_batches(batch_size=...) uses the lazy
        RebatchStage form instead, which defers the work into the
        consumer-driven pipeline."""
        from ray_tpu.data.streaming_executor import RebatchStage

        return self._with_stage(RebatchStage(rows_per_block)).materialize()

    def random_shuffle(self, seed: Optional[int] = None) -> "Dataset":
        """Distributed exchange shuffle (parity: the reference's push-based
        shuffle in ``_internal/planner/exchange/``): each source block is
        split into k random slices by tasks, each output block merges one
        slice from every source and permutes — no global materialization."""
        mat = self.materialize()
        k = max(1, len(mat._block_refs))
        if seed is None:
            import os as _os

            base = int.from_bytes(_os.urandom(4), "little")  # random per call
        else:
            base = int(seed)
        split_refs = [
            _shuffle_split.options(num_returns=k).remote(ref, k, base + i)
            for i, ref in enumerate(mat._block_refs)
        ]
        if k == 1:
            split_refs = [[r] for r in split_refs]
        out = [
            _shuffle_merge.remote(base + 7919 + j, *[row[j] for row in split_refs])
            for j in range(k)
        ]
        return Dataset(out)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        """Distributed range-partition sort (parity: the sort exchange,
        ``python/ray/data/_internal/planner/exchange/sort_task_spec.py:1``):
        sample boundaries -> range-partition map stage -> per-range sorted
        merge, all as tasks over blocks."""
        from ray_tpu.data.aggregate import (
            _range_partition,
            _sample_keys,
            _sort_merge,
        )

        mat = self.materialize()
        if not mat._block_refs:
            return mat  # empty dataset is trivially sorted
        k = len(mat._block_refs)
        if k == 1:
            out = [_sort_merge.remote(key, descending, mat._block_refs[0])]
            return Dataset(out)
        sample_arrays = [
            np.asarray(s)
            for s in ray_tpu.get(
                [_sample_keys.remote(r, key, 32) for r in mat._block_refs],
                timeout=600,
            )
            if len(s)
        ]
        if not sample_arrays:
            return mat  # all blocks empty
        samples = np.concatenate(sample_arrays)
        samples.sort()
        # k-1 boundaries at even quantiles
        bounds = [samples[int(i * len(samples) / k)] for i in range(1, k)]
        parts = [
            _range_partition.options(num_returns=k).remote(ref, key, bounds)
            for ref in mat._block_refs
        ]
        out = [
            _sort_merge.remote(key, descending, *[row[j] for row in parts])
            for j in range(k)
        ]
        if descending:
            out = out[::-1]
        return Dataset(out)

    def groupby(self, key: str):
        """Parity: ``Dataset.groupby`` -> GroupedData (hash exchange)."""
        from ray_tpu.data.aggregate import GroupedData

        return GroupedData(self, key)

    def aggregate(self, *aggs) -> Dict[str, Any]:
        """Global aggregation: per-block partials + driver-side merge."""
        from ray_tpu.data.aggregate import _partial_agg

        import cloudpickle

        mat = self.materialize()
        blobs = [cloudpickle.dumps(a) for a in aggs]
        partials = ray_tpu.get(
            [_partial_agg.remote(ref, blobs) for ref in mat._block_refs],
            timeout=600,
        )
        out = {}
        for i, a in enumerate(aggs):
            acc = a.init()
            for row in partials:
                acc = a.merge(acc, row[i])
            out[a.name] = a.finalize(acc)
        return out

    def sum(self, on: str) -> float:
        from ray_tpu.data.aggregate import Sum

        return self.aggregate(Sum(on))[f"sum({on})"]

    def min(self, on: str) -> float:
        from ray_tpu.data.aggregate import Min

        return self.aggregate(Min(on))[f"min({on})"]

    def max(self, on: str) -> float:
        from ray_tpu.data.aggregate import Max

        return self.aggregate(Max(on))[f"max({on})"]

    def mean(self, on: str) -> float:
        from ray_tpu.data.aggregate import Mean

        return self.aggregate(Mean(on))[f"mean({on})"]

    def std(self, on: str, ddof: int = 1) -> float:
        from ray_tpu.data.aggregate import Std

        return self.aggregate(Std(on, ddof))[f"std({on})"]

    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        ds = self.materialize()
        if equal:
            block = concat_blocks([_fetch(r) for r in ds._block_refs])
            total = block_num_rows(block)
            per = total // n
            return [
                Dataset([ray_tpu.put(slice_block(block, i * per, (i + 1) * per))])
                for i in range(n)
            ]
        shards: List[List] = [[] for _ in range(n)]
        for i, ref in enumerate(ds._block_refs):
            shards[i % n].append(ref)
        return [Dataset(refs) for refs in shards]

    def streaming_split(self, n: int, *, equal: bool = False) -> List["DataIterator"]:
        """Per-consumer iterators over disjoint shards (parity:
        ``dataset.py:1236``; feeds one trainer worker each)."""
        from ray_tpu.data.iterator import DataIterator

        return [DataIterator(shard) for shard in self.split(n, equal=equal)]

    # -- execution ---------------------------------------------------------

    def _iter_exec_block_refs(self) -> Iterator:
        """Drive the streaming executor: all stages run concurrently over
        bounded windows (DataContext.max_inflight_blocks per stage), so a
        dataset arbitrarily larger than memory streams through a consumer
        while every pipeline stage stays busy."""
        from ray_tpu.data.streaming_executor import ReadTask, iter_stage_refs

        if not self._stages and not any(
            isinstance(r, ReadTask) for r in self._block_refs
        ):
            yield from self._block_refs
            return
        self._exec_stats = []
        yield from iter_stage_refs(
            self._block_refs, self._stages, self._owned_actors,
            collector=self._exec_stats,
        )

    def _iter_exec_blocks(self) -> Iterator[Batch]:
        for ref in self._iter_exec_block_refs():
            yield _fetch(ref)

    def materialize(self) -> "Dataset":
        """Execute the plan; returns a Dataset of plain block refs."""
        from ray_tpu.data.streaming_executor import ReadTask

        if not self._stages and not any(
            isinstance(r, ReadTask) for r in self._block_refs
        ):
            return self
        return Dataset(
            list(self._iter_exec_block_refs()), owned_actors=self._owned_actors
        )

    def to_block(self) -> Batch:
        return concat_blocks(list(self._iter_exec_blocks()))

    # -- consumption -------------------------------------------------------

    def count(self) -> int:
        return sum(block_num_rows(b) for b in self._iter_exec_blocks())

    def take(self, n: int = 20) -> List[Dict]:
        out = []
        for block in self._iter_exec_blocks():
            for row in block_to_rows(block):
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[Dict]:
        return [r for b in self._iter_exec_blocks() for r in block_to_rows(b)]

    def iter_rows(self) -> Iterator[Dict]:
        for block in self._iter_exec_blocks():
            yield from block_to_rows(block)

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = False,
    ) -> Iterator[Batch]:
        """Re-batch the block stream to exactly batch_size rows. Linear: each
        row is copied at most once (pieces are sliced views until concat)."""
        import collections

        blocks: collections.deque = collections.deque()  # (block, offset)
        buffered = 0
        for block in self._iter_exec_blocks():
            n = block_num_rows(block)
            if n:
                blocks.append((block, 0))
                buffered += n
            while buffered >= batch_size:
                pieces = []
                need = batch_size
                while need:
                    blk, off = blocks[0]
                    n = block_num_rows(blk) - off
                    take = min(need, n)
                    pieces.append(slice_block(blk, off, off + take))
                    need -= take
                    if take == n:
                        blocks.popleft()
                    else:
                        blocks[0] = (blk, off + take)
                buffered -= batch_size
                yield pieces[0] if len(pieces) == 1 else concat_blocks(pieces)
        if buffered and not drop_last:
            yield concat_blocks([slice_block(b, o, block_num_rows(b)) for b, o in blocks])

    def iter_jax_batches(self, **kw) -> Iterator[Dict]:
        """Parity: the framework batch iterators live on Dataset too (the
        reference's ``Dataset.iter_torch_batches`` family) — delegate to a
        DataIterator over this plan."""
        from ray_tpu.data.iterator import DataIterator

        return DataIterator(self).iter_jax_batches(**kw)

    def iter_tf_batches(self, **kw) -> Iterator[Dict]:
        from ray_tpu.data.iterator import DataIterator

        return DataIterator(self).iter_tf_batches(**kw)

    def iter_torch_batches(self, **kw) -> Iterator[Dict]:
        from ray_tpu.data.iterator import DataIterator

        return DataIterator(self).iter_torch_batches(**kw)

    def to_pandas(self):
        import pandas as pd

        block = self.to_block()
        return pd.DataFrame({k: list(v) if getattr(v, "ndim", 1) > 1 else v
                             for k, v in block.items()})

    def to_arrow(self):
        """Single pyarrow.Table of the whole dataset (parity: to_arrow_refs
        collapsed to one table — the common interop shape). Numeric numpy
        columns wrap zero-copy; object columns convert."""
        return _to_arrow_table(self.to_block())

    def to_arrow_refs(self) -> List:
        """Per-block Arrow conversion as refs (parity: to_arrow_refs)."""
        src_refs, ops = self._refs_and_ops()
        return [_block_to_arrow.remote(r, ops) for r in src_refs]

    def to_numpy_refs(self) -> List:
        return list(self._iter_exec_block_refs())

    # -- writes (parity: Dataset.write_parquet/csv/json — one file per
    # block, written by distributed tasks) --------------------------------

    def _write(self, path: str, ext: str, writer_fn) -> List[str]:
        import cloudpickle

        from ray_tpu._private import external_storage as storage

        if not storage.has_scheme(path):
            os.makedirs(path, exist_ok=True)
        blob = cloudpickle.dumps(writer_fn)
        src_refs, ops = self._refs_and_ops()
        refs = [
            _write_block.remote(
                ref,
                ops,
                storage.join(path, f"part-{i:05d}{ext}")
                if storage.has_scheme(path)
                else os.path.join(path, f"part-{i:05d}{ext}"),
                blob,
            )
            for i, ref in enumerate(src_refs)
        ]
        return ray_tpu.get(refs, timeout=600)

    def write_parquet(self, path: str) -> List[str]:
        def _w(block, out_path):
            import pyarrow as pa
            import pyarrow.parquet as pq

            pq.write_table(pa.table({k: list(v) for k, v in block.items()}), out_path)

        return self._write(path, ".parquet", _w)

    def write_csv(self, path: str) -> List[str]:
        def _w(block, out_path):
            import csv

            cols = list(block)
            with open(out_path, "w", newline="") as fh:
                w = csv.writer(fh)
                w.writerow(cols)
                for i in builtins.range(block_num_rows(block)):
                    w.writerow([block[c][i] for c in cols])

        return self._write(path, ".csv", _w)

    def write_json(self, path: str) -> List[str]:
        def _w(block, out_path):
            import json

            with open(out_path, "w") as fh:
                for row in block_to_rows(block):
                    fh.write(json.dumps({k: v.tolist() if hasattr(v, "tolist") else v
                                         for k, v in row.items()}) + "\n")

        return self._write(path, ".json", _w)

    def schema(self) -> Dict[str, str]:
        for block in self._iter_exec_blocks():
            return {k: str(v.dtype) for k, v in block.items()}
        return {}

    def num_blocks(self) -> int:
        """Block count of the plan's OUTPUT. For lazy plans with
        count-changing stages (rebatch) this requires executing the plan —
        metadata calls on lazy pipelines are rare; prefer asking a
        materialized dataset."""
        from ray_tpu.data.streaming_executor import RebatchStage

        if any(isinstance(s, RebatchStage) for s in self._stages):
            return len(self.materialize()._block_refs)
        return len(self._block_refs)

    def stats(self) -> str:
        """Plan summary + per-stage metrics of THIS dataset's most recent
        execution (parity: ``Dataset.stats()``'s per-operator breakdown —
        block counts, wall time, throughput, mean block size)."""
        lines = [
            f"Dataset(blocks={len(self._block_refs)}, "
            f"stages={len(self._stages)})"
        ]
        own = getattr(self, "_exec_stats", None)
        if own:
            lines.append("Last execution:")
            for st in own[-8:]:
                lines.append("  " + st.render())
        return "\n".join(lines)

    def __repr__(self):
        return self.stats()


@ray_tpu.remote
def _sample_block(block: Batch, fraction: float, base: int, index: int) -> Batch:
    rng = np.random.default_rng([base, index])
    keep = rng.random(block_num_rows(block)) < fraction
    return {k: np.asarray(v)[keep] for k, v in block.items()}


def _to_arrow_table(block: Batch):
    """dict-of-columns block -> pyarrow.Table (zero-copy for contiguous
    numerics; object columns convert element-wise)."""
    import pyarrow as pa

    return pa.table(
        {
            k: pa.array(list(v)) if getattr(v, "dtype", None) is not None
            and v.dtype == object else pa.array(np.asarray(v))
            for k, v in block.items()
        }
    )


@ray_tpu.remote
def _block_to_arrow(block, ops):
    return _to_arrow_table(_apply_ops(block, ops))


@ray_tpu.remote
def _block_unique(block, ops, column: str):
    block = _apply_ops(block, ops)
    return np.unique(np.asarray(block[column])).tolist()


@ray_tpu.remote
def _write_block(block, ops, out_path: str, writer_blob):
    import cloudpickle

    from ray_tpu._private import external_storage as storage

    block = _apply_ops(block, ops)
    writer = cloudpickle.loads(writer_blob)
    if out_path.startswith("file://"):
        # already local: write straight to the resolved path
        local = storage.resolve(out_path)[1]
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        writer(block, local)
    elif storage.has_scheme(out_path):
        # scheme'd target: stage locally, then hand the bytes to the backend
        import tempfile

        suffix = os.path.splitext(out_path)[1]
        with tempfile.NamedTemporaryFile(suffix=suffix, delete=False) as tmp:
            local = tmp.name
        try:
            writer(block, local)
            with open(local, "rb") as fh:
                storage.write_bytes(out_path, fh.read())
        finally:
            try:
                os.unlink(local)
            except OSError:
                pass
    else:
        writer(block, out_path)
    return out_path


@ray_tpu.remote
def _shuffle_split(block: Batch, k: int, seed: int):
    """Randomly partition a block's rows into k slices."""
    n = block_num_rows(block)
    rng = np.random.default_rng(seed)
    assignment = rng.integers(0, k, n)
    out = tuple(
        {key: v[assignment == j] for key, v in block.items()} for j in range(k)
    )
    return out if k > 1 else out[0]


@ray_tpu.remote
def _shuffle_merge(seed: int, *slices: Batch) -> Batch:
    merged = concat_blocks(list(slices))
    n = block_num_rows(merged)
    perm = np.random.default_rng(seed).permutation(n)
    return {k: v[perm] for k, v in merged.items()}


def _fetch(ref) -> Batch:
    if isinstance(ref, ray_tpu.ObjectRef):
        return ray_tpu.get(ref, timeout=120)
    return ref
