"""DataIterator: the per-trainer-worker consumption handle.

Parity: ``python/ray/data/iterator.py`` (``DataIterator.iter_batches``,
``to_tf``/``to_torch`` analogues) — plus ``iter_jax_batches`` which
``device_put``s each batch with an optional sharding, the TPU feed path
(SURVEY.md §7 step 5: blocks -> iter_batches -> device_put sharded).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import numpy as np


class DataIterator:
    def __init__(self, dataset):
        self._ds = dataset

    def iter_batches(self, *, batch_size: int = 256, drop_last: bool = False):
        return self._ds.iter_batches(batch_size=batch_size, drop_last=drop_last)

    def iter_rows(self):
        return self._ds.iter_rows()

    def count(self) -> int:
        return self._ds.count()

    def materialize(self):
        return self._ds.materialize()

    def iter_jax_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = True,
        sharding: Optional[Any] = None,
        dtypes: Optional[Dict[str, Any]] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Batches as (optionally sharded) jax Arrays on device."""
        import jax

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                arr = np.asarray(v)
                if dtypes and k in dtypes:
                    arr = arr.astype(dtypes[k])
                out[k] = jax.device_put(arr, sharding) if sharding is not None else jax.device_put(arr)
            yield out

    def iter_tf_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = False,
        dtypes: Optional[Dict[str, Any]] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Batches as tf tensors (parity: ``iter_tf_batches``)."""
        import tensorflow as tf

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                t = tf.convert_to_tensor(np.asarray(v))
                if dtypes and k in dtypes:
                    t = tf.cast(t, dtypes[k])
                out[k] = t
            yield out

    def iter_torch_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = False,
        dtypes: Optional[Dict[str, Any]] = None,
        device: Optional[str] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Batches as torch tensors (parity: ``iter_torch_batches``)."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(np.asarray(v))
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                if device:
                    t = t.to(device)
                out[k] = t
            yield out
