"""DataIterator: the per-trainer-worker consumption handle.

Parity: ``python/ray/data/iterator.py`` (``DataIterator.iter_batches``,
``to_tf``/``to_torch`` analogues) — plus ``iter_jax_batches`` which
``device_put``s each batch with an optional sharding, the TPU feed path
(SURVEY.md §7 step 5: blocks -> iter_batches -> device_put sharded).

This is also the training step plane's ingest seam: when a step timer is
active (``_private/stepplane``), time spent blocked in ``next()`` lands in
the step's ``data_wait`` stage — attributed to the bottleneck streaming-
executor operator via the pipeline's live backpressure stats — the
``device_put`` in ``iter_jax_batches`` in ``host_to_device``, and every
batch's abstract-shape signature feeds the recompilation detector.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterator, Optional

import numpy as np

# ingest stalls shorter than this are loop noise, not backpressure — they
# accrue to data_wait but skip the per-operator attribution walk
_ATTRIBUTE_STALL_S = 0.002


class DataIterator:
    def __init__(self, dataset):
        self._ds = dataset

    def _bottleneck_operator(self) -> str:
        """The streaming-executor stage the consumer is most plausibly
        waiting on RIGHT NOW: the stage with the deepest in-flight window
        (its backpressure queue is where the pipeline's slack went). Falls
        back to "source" when the dataset has no live execution stats
        (materialized datasets, plain block lists)."""
        stats = getattr(self._ds, "_exec_stats", None) or ()
        best, depth = None, 0
        for st in stats:
            try:
                inflight = st.inflight
            except Exception:
                continue
            if inflight > depth:
                best, depth = st.name, inflight
        return best or "source"

    def iter_batches(self, *, batch_size: int = 256, drop_last: bool = False):
        from ray_tpu._private import stepplane

        it = iter(
            self._ds.iter_batches(batch_size=batch_size, drop_last=drop_last)
        )
        while True:
            timer = stepplane.current()  # re-read: a step may start mid-iter
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            if timer is not None:
                wait = time.perf_counter() - t0
                timer.note_data_wait(
                    wait,
                    self._bottleneck_operator()
                    if wait >= _ATTRIBUTE_STALL_S
                    else None,
                )
                timer.note_batch_signature(stepplane.batch_signature(batch))
            yield batch

    def iter_rows(self):
        return self._ds.iter_rows()

    def count(self) -> int:
        return self._ds.count()

    def materialize(self):
        return self._ds.materialize()

    def iter_jax_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = True,
        sharding: Optional[Any] = None,
        dtypes: Optional[Dict[str, Any]] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Batches as (optionally sharded) jax Arrays on device."""
        import jax

        from ray_tpu._private import stepplane

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            t0 = time.perf_counter()
            out = {}
            for k, v in batch.items():
                arr = np.asarray(v)
                if dtypes and k in dtypes:
                    arr = arr.astype(dtypes[k])
                out[k] = jax.device_put(arr, sharding) if sharding is not None else jax.device_put(arr)
            timer = stepplane.current()
            if timer is not None:
                timer.note_host_to_device(time.perf_counter() - t0)
            yield out

    def iter_tf_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = False,
        dtypes: Optional[Dict[str, Any]] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Batches as tf tensors (parity: ``iter_tf_batches``)."""
        import tensorflow as tf

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                t = tf.convert_to_tensor(np.asarray(v))
                if dtypes and k in dtypes:
                    t = tf.cast(t, dtypes[k])
                out[k] = t
            yield out

    def iter_torch_batches(
        self,
        *,
        batch_size: int = 256,
        drop_last: bool = False,
        dtypes: Optional[Dict[str, Any]] = None,
        device: Optional[str] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Batches as torch tensors (parity: ``iter_torch_batches``)."""
        import torch

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(np.asarray(v))
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                if device:
                    t = t.to(device)
                out[k] = t
            yield out
