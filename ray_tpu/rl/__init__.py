"""Reinforcement learning library (RLlib equivalent, new-stack shape).

Parity: ``rllib/`` (SURVEY.md §2.4) — ``Algorithm``/``AlgorithmConfig``
(``algorithms/algorithm.py:229``), EnvRunner actors sampling episodes
(``env/single_agent_env_runner.py:131``), a Learner holding the jitted update
(``core/learner/``). The torch-DDP learner group
(``torch_learner.py:397``) becomes one SPMD jit program; env runners stay CPU
actors. In-tree algorithms: PPO (CartPole learning target: return >= 150,
``tuned_examples/ppo/cartpole-ppo.yaml:5-7``).
"""

from ray_tpu.rl.appo import APPO, APPOConfig
from ray_tpu.rl.connectors import (
    ClipActions,
    Connector,
    ConnectorPipeline,
    FrameStack,
    NormalizeObservations,
)
from ray_tpu.rl.dqn import DQN, DQNConfig
from ray_tpu.rl.env import CartPoleEnv, EnvSpec, make_env, register_env
from ray_tpu.rl.impala import IMPALA, IMPALAConfig
from ray_tpu.rl.multi_agent import (
    MultiAgentCartPole,
    MultiAgentEnv,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rl.sac import SAC, SACConfig
from ray_tpu.rl.offline import BC, CQL, MARWIL, BCConfig, CQLConfig, MARWILConfig
from ray_tpu.rl.ppo import PPO, PPOConfig

__all__ = [
    "PPO",
    "PPOConfig",
    "APPO",
    "APPOConfig",
    "IMPALA",
    "IMPALAConfig",
    "CQL",
    "CQLConfig",
    "DQN",
    "DQNConfig",
    "SAC",
    "SACConfig",
    "MultiAgentEnv",
    "MultiAgentCartPole",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "BC",
    "BCConfig",
    "MARWIL",
    "MARWILConfig",
    "CartPoleEnv",
    "make_env",
    "register_env",
    "EnvSpec",
    "Connector",
    "ConnectorPipeline",
    "NormalizeObservations",
    "FrameStack",
    "ClipActions",
]

from ray_tpu._private import usage as _usage

_usage.record_library_usage("rl")
del _usage
