"""Connector pipelines: composable transforms between env and module.

Parity: ``rllib/connectors/`` (new-stack ConnectorV2) — env-to-module
pipelines transform raw observations before the policy consumes them (and
before they are stored in the rollout, so training sees exactly what acting
saw), module-to-env pipelines transform actions on the way back. Stateful
connectors (running obs normalization, frame stacking) carry their state
through ``get_state``/``set_state`` and ride algorithm checkpoints.

Each runner holds its own pipeline instance (the reference merges per-runner
connector states periodically; here runner-local state is kept — exact for
single-runner setups, approximate for many, same as the reference between
merges).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Connector:
    """One transform stage. ``__call__`` receives a batch of observations
    (N, obs_dim) plus the per-lane done mask of the PREVIOUS step (stateful
    connectors reset those lanes)."""

    def __call__(self, obs: np.ndarray, dones: Optional[np.ndarray] = None) -> np.ndarray:
        return obs

    def transform_action(self, actions: np.ndarray) -> np.ndarray:
        return actions

    def out_dim(self, in_dim: int) -> int:
        return in_dim

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class ConnectorPipeline(Connector):
    """Ordered composition (parity: ConnectorPipelineV2)."""

    def __init__(self, connectors: List[Connector]):
        self.connectors = list(connectors)

    def __call__(self, obs, dones=None):
        for c in self.connectors:
            obs = c(obs, dones)
        return obs

    def transform_action(self, actions):
        for c in reversed(self.connectors):
            actions = c.transform_action(actions)
        return actions

    def out_dim(self, in_dim: int) -> int:
        for c in self.connectors:
            in_dim = c.out_dim(in_dim)
        return in_dim

    def get_state(self):
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state):
        for i, c in enumerate(self.connectors):
            if i in state or str(i) in state:
                c.set_state(state.get(i, state.get(str(i))))


class NormalizeObservations(Connector):
    """Running mean/std observation filter (parity:
    ``connectors/env_to_module/mean_std_filter.py``; Welford batched)."""

    def __init__(self, epsilon: float = 1e-8, clip: float = 10.0):
        self.eps = epsilon
        self.clip = clip
        self.count = 0.0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None

    def __call__(self, obs, dones=None):
        obs = np.asarray(obs, np.float64)
        n = obs.shape[0]
        if self.mean is None:
            self.mean = np.zeros(obs.shape[-1])
            self.m2 = np.ones(obs.shape[-1])
        batch_mean = obs.mean(axis=0)
        batch_m2 = ((obs - batch_mean) ** 2).sum(axis=0)
        delta = batch_mean - self.mean
        total = self.count + n
        self.mean = self.mean + delta * n / total
        self.m2 = self.m2 + batch_m2 + delta**2 * self.count * n / total
        self.count = total
        var = self.m2 / max(self.count, 2.0)
        out = (obs - self.mean) / np.sqrt(var + self.eps)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def get_state(self):
        return {
            "count": self.count,
            "mean": None if self.mean is None else self.mean.copy(),
            "m2": None if self.m2 is None else self.m2.copy(),
        }

    def set_state(self, state):
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]


class FrameStack(Connector):
    """Concatenate the last ``k`` observations per env lane (parity:
    ``connectors/env_to_module/frame_stacking.py``); lanes reset on done."""

    def __init__(self, k: int = 4):
        self.k = int(k)
        self._buf: Optional[np.ndarray] = None  # (N, k, obs_dim)

    def __call__(self, obs, dones=None):
        obs = np.asarray(obs, np.float32)
        n, d = obs.shape
        if self._buf is None or self._buf.shape[0] != n:
            self._buf = np.repeat(obs[:, None, :], self.k, axis=1)
        elif dones is not None and dones.any():
            idx = np.nonzero(dones)[0]
            self._buf[idx] = obs[idx, None, :]
        self._buf = np.concatenate([self._buf[:, 1:], obs[:, None, :]], axis=1)
        return self._buf.reshape(n, self.k * d)

    def out_dim(self, in_dim: int) -> int:
        return in_dim * self.k

    def get_state(self):
        return {"buf": None if self._buf is None else self._buf.copy()}

    def set_state(self, state):
        self._buf = state["buf"]


class ClipActions(Connector):
    """Module-to-env action clipping (parity:
    ``connectors/module_to_env/...``; no-op for discrete actions)."""

    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def transform_action(self, actions):
        if np.issubdtype(np.asarray(actions).dtype, np.floating):
            return np.clip(actions, self.low, self.high)
        return actions
