"""EnvRunner: samples rollouts with the current policy.

Parity: ``SingleAgentEnvRunner.sample`` (``rllib/env/single_agent_env_runner.py:131``)
— remote actors (or a driver-local runner for ``num_env_runners=0``) stepping
vectorized envs with jitted policy inference; the EnvRunnerGroup tolerates
runner loss (``rllib/utils/actor_manager.py`` role).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import ray_tpu


def resolve_obs_dim(config, spec) -> int:
    """Module input width after the env-to-module pipeline (FrameStack etc.
    widen observations; the policy net must be built for the OUTPUT)."""
    factory = getattr(config, "env_to_module_connector", None)
    if factory is None:
        return spec.obs_dim
    return _build_pipeline(factory).out_dim(spec.obs_dim)


def _build_pipeline(connectors):
    if connectors is None:
        return None
    from ray_tpu.rl.connectors import Connector, ConnectorPipeline

    if callable(connectors) and not isinstance(connectors, Connector):
        connectors = connectors()  # per-runner factory
    if isinstance(connectors, ConnectorPipeline):
        return connectors
    if isinstance(connectors, Connector):
        return ConnectorPipeline([connectors])
    return ConnectorPipeline(list(connectors))


class EnvRunner:
    """Plain class; wrapped as a remote actor by EnvRunnerGroup."""

    def __init__(self, env_creator, num_envs: int, rollout_len: int, seed: int,
                 connectors=None):
        from ray_tpu.train.jax_utils import ensure_platform

        ensure_platform()  # runners must not grab the accelerator
        import jax

        from ray_tpu.rl.env import VectorEnv
        from ray_tpu.rl.models import sample_actions

        self._jax = jax
        self.vec = VectorEnv(env_creator, num_envs, seed=seed)
        self.rollout_len = rollout_len
        # env-to-module connector pipeline (parity: rllib/connectors/):
        # observations are transformed before the policy sees them AND
        # before they land in the rollout, so learning matches acting
        self.connectors = _build_pipeline(connectors)
        raw = self.vec.reset()
        self.obs = self.connectors(raw) if self.connectors else raw
        self.key = jax.random.PRNGKey(seed)
        self._sample_fn = jax.jit(sample_actions)
        # per-env episode bookkeeping for return metrics
        self._ep_return = np.zeros(num_envs)
        self._completed: List[float] = []

    def get_connector_state(self):
        """Trained connector-pipeline state (normalize stats etc.) for
        evaluation-time reuse; None when no pipeline is configured."""
        return self.connectors.get_state() if self.connectors else None

    def sample(self, params) -> Dict[str, np.ndarray]:
        jax = self._jax
        T, N = self.rollout_len, self.vec.n
        obs_buf = np.empty((T, N, self.obs.shape[-1]), np.float32)
        act_buf = np.empty((T, N), np.int32)
        logp_buf = np.empty((T, N), np.float32)
        val_buf = np.empty((T, N), np.float32)
        rew_buf = np.empty((T, N), np.float32)
        done_buf = np.empty((T, N), bool)
        for t in range(T):
            self.key, sub = jax.random.split(self.key)
            actions, logp, value = self._sample_fn(params, self.obs, sub)
            actions = np.asarray(actions)
            obs_buf[t] = self.obs
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            env_actions = (
                self.connectors.transform_action(actions)
                if self.connectors
                else actions
            )
            raw, rew, done = self.vec.step(env_actions)
            self.obs = self.connectors(raw, dones=done) if self.connectors else raw
            rew_buf[t] = rew
            done_buf[t] = done
            self._ep_return += rew
            for i in np.nonzero(done)[0]:
                self._completed.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
        # bootstrap value for the final observation
        self.key, sub = jax.random.split(self.key)
        _, _, last_val = self._sample_fn(params, self.obs, sub)
        episode_returns, self._completed = self._completed, []
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "logp": logp_buf,
            "values": val_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "last_values": np.asarray(last_val),
            "episode_returns": np.array(episode_returns, np.float32),
        }


RemoteEnvRunner = ray_tpu.remote(EnvRunner)


class EnvRunnerGroup:
    """num_env_runners remote runners, or one local (in-driver) runner.

    Elastic fault tolerance (parity: ``FaultTolerantActorManager``,
    ``rllib/utils/actor_manager.py:1``): dead runners are dropped on sample
    and ``restore()`` replaces them up to the configured count, so sampling
    survives runner loss and heals."""

    def __init__(self, env_creator, num_env_runners: int, num_envs_per_runner: int,
                 rollout_len: int, seed: int = 0, connectors=None):
        self.local: Optional[EnvRunner] = None
        self.remote: List = []
        self._env_creator = env_creator
        self._num_envs = num_envs_per_runner
        self._rollout_len = rollout_len
        self._seed = seed
        self._connectors = connectors  # factory: fresh pipeline per runner
        self._target = num_env_runners
        self._spawned = 0
        if num_env_runners == 0:
            self.local = EnvRunner(
                env_creator, num_envs_per_runner, rollout_len, seed,
                connectors=connectors,
            )
        else:
            for _ in range(num_env_runners):
                self._spawn()

    def _spawn(self):
        self._spawned += 1
        self.remote.append(
            RemoteEnvRunner.remote(
                self._env_creator,
                self._num_envs,
                self._rollout_len,
                self._seed + 1000 * self._spawned,
                connectors=self._connectors,
            )
        )

    def num_healthy(self) -> int:
        return 1 if self.local is not None else len(self.remote)

    def connector_state(self):
        """The trained env-to-module connector state, wherever the runners
        live: the local runner's pipeline state, or the first healthy
        remote runner's (remote runners see the same stream statistics)."""
        if self.local is not None:
            return self.local.get_connector_state()
        for r in list(self.remote):
            try:
                return ray_tpu.get(r.get_connector_state.remote(), timeout=60)
            except Exception:
                continue
        return None

    def restore(self, min_runners: Optional[int] = None) -> int:
        """Replace dead runners up to the original target; returns how many
        fresh runners were started."""
        if self.local is not None:
            return 0
        want = self._target if min_runners is None else min_runners
        started = 0
        while len(self.remote) < want:
            self._spawn()
            started += 1
        return started

    def sample(self, params) -> List[Dict[str, np.ndarray]]:
        if self.local is not None:
            return [self.local.sample(params)]
        host_params = _to_host(params)
        refs = [r.sample.remote(host_params) for r in self.remote]
        out = []
        for r, ref in zip(list(self.remote), refs):
            try:
                out.append(ray_tpu.get(ref, timeout=300))
            except Exception:
                # elastic sampling: drop the dead runner, keep the rest
                self.remote.remove(r)
        if not out:
            raise RuntimeError("all env runners failed")
        return out

    def stop(self):
        for r in self.remote:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass


def _to_host(params):
    import jax

    return jax.tree.map(lambda x: np.asarray(x), params)
