"""Algorithm base + fluent config.

Parity: ``rllib/algorithms/algorithm.py:229`` (Tune-Trainable shape:
``train()`` returns a result dict; ``save``/``restore``) and the fluent
``AlgorithmConfig`` (``algorithm_config.py``): ``.environment(...)``
``.env_runners(...)`` ``.training(...)`` ``.build()``.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional


class AlgorithmConfig:
    def __init__(self):
        self.env = "CartPole-v1"
        self.num_env_runners = 0
        self.num_envs_per_runner = 16
        self.rollout_len = 128
        self.lr = 3e-4
        self.gamma = 0.99
        self.seed = 0
        self.hidden = (64, 64)
        # zero-arg factory -> connector list/pipeline (see env_runners)
        self.env_to_module_connector = None

    def environment(self, env) -> "AlgorithmConfig":
        self.env = env
        return self

    def env_runners(self, num_env_runners: int = 0, num_envs_per_env_runner: int = 16,
                    rollout_fragment_length: int = 128,
                    env_to_module_connector=None) -> "AlgorithmConfig":
        """``env_to_module_connector``: zero-arg factory returning a list of
        connectors (or a ConnectorPipeline) applied to observations before
        the module sees/stores them — one fresh instance per runner (parity:
        AlgorithmConfig.env_runners(env_to_module_connector=...),
        rllib/connectors/)."""
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_len = rollout_fragment_length
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training option {k}")
            setattr(self, k, v)
        return self

    def debugging(self, seed: int = 0) -> "AlgorithmConfig":
        self.seed = seed
        return self

    def build(self):
        raise NotImplementedError


class Algorithm:
    """Base: iteration counter, checkpointing, Tune-compatible train()."""

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        result = self.training_step()
        result["training_iteration"] = self.iteration
        return result

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        raise NotImplementedError

    def set_state(self, state: Dict[str, Any]) -> None:
        raise NotImplementedError

    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as fh:
            pickle.dump({"iteration": self.iteration, "state": self.get_state()}, fh)
        return path

    def restore(self, path: str) -> None:
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as fh:
            blob = pickle.load(fh)
        self.iteration = blob["iteration"]
        self.set_state(blob["state"])

    def stop(self) -> None:
        pass
