"""Algorithm base + fluent config.

Parity: ``rllib/algorithms/algorithm.py:229`` (Tune-Trainable shape:
``train()`` returns a result dict; ``save``/``restore``) and the fluent
``AlgorithmConfig`` (``algorithm_config.py``): ``.environment(...)``
``.env_runners(...)`` ``.training(...)`` ``.build()``.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional


class AlgorithmConfig:
    def __init__(self):
        self.env = "CartPole-v1"
        self.num_env_runners = 0
        self.num_envs_per_runner = 16
        self.rollout_len = 128
        self.lr = 3e-4
        self.gamma = 0.99
        self.seed = 0
        self.hidden = (64, 64)
        # zero-arg factory -> connector list/pipeline (see env_runners)
        self.env_to_module_connector = None

    def environment(self, env) -> "AlgorithmConfig":
        self.env = env
        return self

    def env_runners(self, num_env_runners: int = 0, num_envs_per_env_runner: int = 16,
                    rollout_fragment_length: int = 128,
                    env_to_module_connector=None) -> "AlgorithmConfig":
        """``env_to_module_connector``: zero-arg factory returning a list of
        connectors (or a ConnectorPipeline) applied to observations before
        the module sees/stores them — one fresh instance per runner (parity:
        AlgorithmConfig.env_runners(env_to_module_connector=...),
        rllib/connectors/)."""
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_len = rollout_fragment_length
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training option {k}")
            setattr(self, k, v)
        return self

    def debugging(self, seed: int = 0) -> "AlgorithmConfig":
        self.seed = seed
        return self

    def build(self):
        raise NotImplementedError


class Algorithm:
    """Base: iteration counter, checkpointing, Tune-compatible train()."""

    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0

    def train(self) -> Dict[str, Any]:
        self.iteration += 1
        result = self.training_step()
        result["training_iteration"] = self.iteration
        return result

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        raise NotImplementedError

    def set_state(self, state: Dict[str, Any]) -> None:
        raise NotImplementedError

    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "algorithm_state.pkl"), "wb") as fh:
            pickle.dump({"iteration": self.iteration, "state": self.get_state()}, fh)
        return path

    def restore(self, path: str) -> None:
        with open(os.path.join(path, "algorithm_state.pkl"), "rb") as fh:
            blob = pickle.load(fh)
        self.iteration = blob["iteration"]
        self.set_state(blob["state"])

    # -- inference / evaluation (parity: Algorithm.compute_single_action
    # and the evaluation rollout surface, rllib/algorithms/algorithm.py) --

    def _policy_params(self):
        """The MLP-policy param tree actions come from. Policy-gradient
        algos expose ``self.params``; SAC's actor is ``self.actor``."""
        params = getattr(self, "params", None)
        if params is None:
            params = getattr(self, "actor", None)
        if params is None:
            raise NotImplementedError(
                f"{type(self).__name__} does not expose policy params for "
                "single-action inference"
            )
        return params

    def compute_single_action(self, obs, explore: bool = False) -> int:
        """Action for one MODULE-space observation — i.e. after any
        configured env-to-module connector pipeline has transformed it
        (``evaluate`` does this; raw-obs callers with connectors must run
        the pipeline themselves, since the net is built for its output
        width). ``explore=False`` is greedy (argmax over the policy/Q
        logits); ``explore=True`` samples, seeded from ``config.seed``."""
        import numpy as np

        fwd = getattr(self, "_single_action_logits", None)
        if fwd is None:
            import jax

            from ray_tpu.rl.models import apply_mlp_policy

            fwd = self._single_action_logits = jax.jit(
                lambda p, o: apply_mlp_policy(p, o)[0]
            )
        logits = np.asarray(
            fwd(self._policy_params(), np.asarray(obs, np.float32)[None])
        )[0]
        if explore:
            rng = getattr(self, "_explore_rng", None)
            if rng is None:
                rng = self._explore_rng = np.random.default_rng(
                    getattr(self.config, "seed", 0)
                )
            z = rng.gumbel(size=logits.shape)
            return int(np.argmax(logits + z))
        return int(np.argmax(logits))

    def evaluate(self, num_episodes: int = 5, seed: int = 10_000,
                 max_steps_per_episode: int = 1000) -> Dict[str, Any]:
        """Greedy evaluation rollouts on fresh envs, with the configured
        env-to-module connector pipeline applied exactly as the training
        runners apply it (parity: evaluation_interval rollouts)."""
        import copy

        import numpy as np

        from ray_tpu.rl.env import make_env
        from ray_tpu.rl.env_runner import _build_pipeline

        # use the TRAINED connector state (a NormalizeObservations filter's
        # running mean/std lives in the training runners — local OR remote),
        # loaded into a private pipeline so evaluation does not mutate it
        pipe = _build_pipeline(
            getattr(self.config, "env_to_module_connector", None)
        )
        if pipe is not None:
            # ALWAYS a private copy: when the config holds connector
            # INSTANCES (not a factory), _build_pipeline wraps the same
            # objects the training runners use — evaluation must not
            # advance their statistics or resize their buffers
            pipe = copy.deepcopy(pipe)
        runners = getattr(self, "runners", None)
        if pipe is not None and runners is not None:
            state = getattr(runners, "connector_state", lambda: None)()
            if state is not None:
                pipe.set_state(copy.deepcopy(state))
        returns = []
        lengths = []
        for ep in range(num_episodes):
            env = make_env(self.config.env, seed=seed + ep)
            try:
                # callable creators ignore make_env's seed: reseed on reset
                obs = env.reset(seed=seed + ep)[0]
            except TypeError:
                obs = env.reset()[0]
            total, steps = 0.0, 0
            for _ in range(max_steps_per_episode):
                raw = np.asarray(obs, np.float32)[None]
                mod_obs = pipe(raw)[0] if pipe else raw[0]
                action = self.compute_single_action(mod_obs)
                if pipe:
                    action = int(pipe.transform_action(np.asarray([action]))[0])
                obs, reward, term, trunc, _ = env.step(action)
                total += float(reward)
                steps += 1
                if term or trunc:
                    break
            returns.append(total)
            lengths.append(steps)
        return {
            "evaluation": {
                "episode_return_mean": float(np.mean(returns)),
                "episode_return_min": float(np.min(returns)),
                "episode_return_max": float(np.max(returns)),
                "episode_len_mean": float(np.mean(lengths)),
                "episodes_this_iter": num_episodes,
            }
        }

    def stop(self) -> None:
        pass
