"""RL policy/value networks (pure jax).

Parity: RLlib's ``RLModule`` role (``rllib/core/rl_module/``) — the
policy+value function behind both sampling and learning, with explicit params
so env runners and learners exchange plain pytrees.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp


def init_mlp_policy(key, obs_dim: int, num_actions: int, hidden: Tuple[int, ...] = (64, 64)):
    sizes = (obs_dim,) + tuple(hidden)
    params = {"layers": [], "pi": None, "vf": None}
    keys = jax.random.split(key, len(hidden) + 2)
    for i in range(len(hidden)):
        w = jax.random.normal(keys[i], (sizes[i], sizes[i + 1])) * jnp.sqrt(2.0 / sizes[i])
        params["layers"].append({"w": w, "b": jnp.zeros(sizes[i + 1])})
    params["pi"] = {
        "w": jax.random.normal(keys[-2], (sizes[-1], num_actions)) * 0.01,
        "b": jnp.zeros(num_actions),
    }
    params["vf"] = {
        "w": jax.random.normal(keys[-1], (sizes[-1], 1)) * 1.0,
        "b": jnp.zeros(1),
    }
    return params


def apply_mlp_policy(params, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs (B, obs_dim) -> (logits (B, A), value (B,))."""
    h = obs
    for layer in params["layers"]:
        h = jnp.tanh(h @ layer["w"] + layer["b"])
    logits = h @ params["pi"]["w"] + params["pi"]["b"]
    value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
    return logits, value


def sample_actions(params, obs, key):
    logits, value = apply_mlp_policy(params, obs)
    actions = jax.random.categorical(key, logits)
    logp = jax.nn.log_softmax(logits)[jnp.arange(obs.shape[0]), actions]
    return actions, logp, value
