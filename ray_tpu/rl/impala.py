"""IMPALA: asynchronous actor-learner RL with V-trace off-policy correction.

Parity: ``rllib/algorithms/impala/impala.py:1`` (actor-learner decoupling,
V-trace from Espeholt et al. 2018) + the multi-learner group
(``rllib/core/learner/learner_group.py:83``). TPU-first translation: instead
of N torch-DDP learner processes exchanging NCCL allreduces, the learner
update is ONE jitted SPMD program over a ``jax.sharding.Mesh`` — the batch is
sharded across the ``data`` axis and XLA inserts the gradient reductions over
ICI (SURVEY.md §2.3 "RLlib learner DP").

Env-runner fault tolerance mirrors ``rllib/utils/actor_manager.py:1``: dead
runners are detected on sample, dropped, and replaced, so sampling is elastic
under runner loss.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import make_env
from ray_tpu.rl.env_runner import EnvRunnerGroup
from ray_tpu.rl.models import apply_mlp_policy, init_mlp_policy


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 6e-4
        self.entropy_coeff = 0.01
        self.vf_loss_coeff = 0.5
        self.grad_clip = 40.0
        self.vtrace_clip_rho = 1.0
        self.vtrace_clip_c = 1.0
        # learner SPMD width: devices the one-program learner group spans
        self.num_learner_devices = 1
        # >1: that many learner *processes* (actors on cluster nodes) join
        # one jax.distributed mesh — the multi-host learner group (parity:
        # rllib/core/learner/learner_group.py:154-174)
        self.num_learner_workers = 1
        self.learner_runtime_env = None
        self.num_cpus_per_learner = 1.0

    def learners(
        self,
        num_learner_devices: int = 1,
        num_learner_workers: int = 1,
        learner_runtime_env=None,
        num_cpus_per_learner: float = 1.0,
    ) -> "IMPALAConfig":
        self.num_learner_devices = num_learner_devices
        self.num_learner_workers = num_learner_workers
        self.learner_runtime_env = learner_runtime_env
        self.num_cpus_per_learner = num_cpus_per_learner
        return self

    def build(self) -> "IMPALA":
        return IMPALA(self)


def vtrace_targets(
    values, last_values, rewards, dones, rhos, gamma, clip_rho=1.0, clip_c=1.0
):
    """V-trace targets vs_t and policy-gradient advantages (jax, scan-based).

    values/rewards/dones/rhos: (T, N); last_values: (N,).
    Returns (vs (T,N), pg_adv (T,N)).
    """
    import jax
    import jax.numpy as jnp

    rho_bar = jnp.minimum(rhos, clip_rho)
    c_bar = jnp.minimum(rhos, clip_c)
    discounts = gamma * (1.0 - dones.astype(jnp.float32))
    values_next = jnp.concatenate([values[1:], last_values[None]], axis=0)
    deltas = rho_bar * (rewards + discounts * values_next - values)

    def scan_fn(acc, xs):
        delta, discount, c = xs
        acc = delta + discount * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        scan_fn,
        jnp.zeros_like(last_values),
        (deltas[::-1], discounts[::-1], c_bar[::-1]),
    )
    vs_minus_v = vs_minus_v[::-1]
    vs = values + vs_minus_v
    vs_next = jnp.concatenate([vs[1:], last_values[None]], axis=0)
    pg_adv = rho_bar * (rewards + discounts * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


def build_impala_update(cfg_vals: Dict[str, Any], optimizer):
    """The IMPALA learner update as a pure function of plain config values —
    shared by the in-process SPMD learner and the multi-host learner-group
    workers (which can't capture an Algorithm instance)."""
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, batch):
        T, N = batch["actions"].shape
        obs = batch["obs"].reshape(T * N, -1)
        logits, values = apply_mlp_policy(params, obs)
        logits = logits.reshape(T, N, -1)
        values = values.reshape(T, N)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1
        )[..., 0]
        rhos = jnp.exp(logp - batch["logp"])  # pi / mu
        vs, pg_adv = vtrace_targets(
            values,
            batch["last_values"],
            batch["rewards"],
            batch["dones"],
            rhos,
            cfg_vals["gamma"],
            cfg_vals["vtrace_clip_rho"],
            cfg_vals["vtrace_clip_c"],
        )
        # mask out env lanes padded up to the mesh multiple — their
        # zero-filled transitions must not bias the gradient
        w = batch["mask"][None, :]  # (1, N) broadcast over T
        denom = jnp.maximum(jnp.sum(w) * T, 1.0)
        pg_loss = -jnp.sum(logp * pg_adv * w) / denom
        vf_loss = 0.5 * jnp.sum(((values - vs) ** 2) * w) / denom
        entropy = (
            -jnp.sum(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1) * w) / denom
        )
        loss = (
            pg_loss
            + cfg_vals["vf_loss_coeff"] * vf_loss
            - cfg_vals["entropy_coeff"] * entropy
        )
        return loss, {
            "pg_loss": pg_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }

    def update(params, opt_state, batch):
        grads, metrics = jax.grad(loss_fn, has_aux=True)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, metrics

    return update


def impala_batch_shardings(mesh):
    """NamedShardings for one learner batch over a ``data``-axis mesh."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    replicated = NamedSharding(mesh, P())
    batch_sharded = NamedSharding(mesh, P(None, "data"))  # (T, N, ...)
    n_sharded = NamedSharding(mesh, P("data"))  # (N,)
    return replicated, {
        "obs": batch_sharded,
        "actions": batch_sharded,
        "logp": batch_sharded,
        "rewards": batch_sharded,
        "dones": batch_sharded,
        "last_values": n_sharded,
        "mask": n_sharded,
    }


def resolve_update_builder(name: str):
    """Update-builder registry shared with the multi-host learner workers
    (which receive the NAME, not a closure, in their builder config)."""
    if name == "appo":
        from ray_tpu.rl.appo import build_appo_update

        return build_appo_update
    return build_impala_update


class IMPALA(Algorithm):
    # subclasses (APPO) swap the jitted learner update
    @classmethod
    def _update_builder_name(cls) -> str:
        return "impala"

    @classmethod
    def _extra_cfg_vals(cls, config) -> Dict[str, Any]:
        return {}

    def __init__(self, config: IMPALAConfig):
        super().__init__(config)
        import jax
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        self._jax = jax
        probe = make_env(config.env)
        spec = probe.spec
        from ray_tpu.rl.env_runner import resolve_obs_dim

        obs_dim = resolve_obs_dim(config, spec)
        self.params = init_mlp_policy(
            jax.random.PRNGKey(config.seed), obs_dim, spec.num_actions, config.hidden
        )
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip), optax.adam(config.lr)
        )
        self.opt_state = self.optimizer.init(self.params)
        self.runners = EnvRunnerGroup(
            config.env,
            config.num_env_runners,
            config.num_envs_per_runner,
            config.rollout_len,
            seed=config.seed,
            connectors=getattr(config, "env_to_module_connector", None),
        )

        self._cfg_vals = {
            "gamma": config.gamma,
            "vtrace_clip_rho": config.vtrace_clip_rho,
            "vtrace_clip_c": config.vtrace_clip_c,
            "vf_loss_coeff": config.vf_loss_coeff,
            "entropy_coeff": config.entropy_coeff,
            **self._extra_cfg_vals(config),
        }
        self._group = None
        if int(config.num_learner_workers) > 1:
            # --- multi-host learner group: N actor processes, one mesh ---
            from ray_tpu.rl.learner_group import SPMDLearnerGroup

            self._group = SPMDLearnerGroup(
                num_workers=int(config.num_learner_workers),
                builder_config={
                    "cfg_vals": dict(self._cfg_vals),
                    "update_builder": self._update_builder_name(),
                    "obs_dim": obs_dim,
                    "num_actions": spec.num_actions,
                    "hidden": config.hidden,
                    "lr": config.lr,
                    "grad_clip": config.grad_clip,
                    "seed": config.seed,
                },
                runtime_env=config.learner_runtime_env,
                num_cpus_per_worker=config.num_cpus_per_learner,
            )
            self._mesh = None
            self._total_learner_devices = self._group.total_devices
        else:
            # --- in-process SPMD learner: one program over a data mesh ---
            n_dev = max(1, int(config.num_learner_devices))
            devices = jax.devices()[:n_dev]
            if len(devices) < n_dev:
                raise ValueError(f"need {n_dev} devices, have {len(devices)}")
            self._mesh = Mesh(np.array(devices), ("data",))
            replicated, batch_shardings = impala_batch_shardings(self._mesh)
            self._update = jax.jit(
                resolve_update_builder(self._update_builder_name())(
                    self._cfg_vals, self.optimizer
                ),
                in_shardings=(replicated, replicated, batch_shardings),
                out_shardings=(replicated, replicated, replicated),
            )
            self._total_learner_devices = n_dev
        self._recent_returns: List[float] = []
        self._timesteps = 0
        self._device_batch = None

    # -- training ----------------------------------------------------------

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        rollouts = self.runners.sample(self.params)
        self.runners.restore(min_runners=None)  # replace any dead runners
        # concatenate runner rollouts along the env axis
        batch = {
            k: np.concatenate([r[k] for r in rollouts], axis=-1 if k == "last_values" else 1)
            for k in ("obs", "actions", "logp", "rewards", "dones")
        }
        batch["last_values"] = np.concatenate([r["last_values"] for r in rollouts])
        for r in rollouts:
            self._recent_returns.extend(r["episode_returns"].tolist())
        self._recent_returns = self._recent_returns[-100:]
        T, N = batch["actions"].shape
        # pad N to a multiple of the mesh so shards are equal; a mask keeps
        # the padded lanes out of the loss
        n_dev = self._total_learner_devices
        pad = (-N) % n_dev
        batch["mask"] = np.ones(N, np.float32)
        if pad:
            for k, v in batch.items():
                env_axis = 0 if k in ("last_values", "mask") else 1
                widths = [(0, 0)] * v.ndim
                widths[env_axis] = (0, pad)
                batch[k] = np.pad(v, widths)
        batch = {
            k: v.astype(np.float32) if v.dtype == bool else v for k, v in batch.items()
        }
        if self._group is not None:
            metrics = self._group.update(batch)
            self.params = self._group.cached_params()
        else:
            self.params, self.opt_state, metrics = self._update(
                self.params, self.opt_state, batch
            )
        self._timesteps += T * N
        mean_ret = (
            float(np.mean(self._recent_returns)) if self._recent_returns else 0.0
        )
        return {
            "episode_return_mean": mean_ret,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "num_healthy_workers": self.runners.num_healthy(),
            **{k: float(v) for k, v in metrics.items()},
        }

    # -- checkpointing (Tune-Trainable shape) ------------------------------

    def get_state(self):
        import jax

        return {
            "params": jax.tree.map(lambda x: np.asarray(x), self.params),
            "timesteps": self._timesteps,
        }

    def set_state(self, state):
        self.params = state["params"]
        self._timesteps = state.get("timesteps", 0)
        if self._group is not None:
            self._group.set_params(self.params)

    def stop(self):
        self.runners.stop()
        if self._group is not None:
            self._group.stop()
