"""DQN: off-policy Q-learning with replay and a target network.

Parity: ``rllib/algorithms/dqn/`` — epsilon-greedy exploration, uniform
replay buffer, Huber TD loss against a periodically-synced target network.
TPU-native translation: the update is ONE jitted program (double-Q target
computation + gradient step fused); sampling stays on CPU env runners.
Learning target parity: the reference's tuned CartPole DQN example
(``rllib/tuned_examples/dqn/cartpole-dqn.yaml``) stops at return >= 150.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import VectorEnv, make_env
from ray_tpu.rl.models import apply_mlp_policy, init_mlp_policy


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_size = 50_000
        self.learning_starts = 1_000
        self.train_batch_size = 64
        self.target_update_freq = 500  # env steps between target syncs
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 5_000
        self.double_q = True
        self.updates_per_iter = 64
        self.steps_per_iter = 512

    def build(self) -> "DQN":
        return DQN(self)


class _ReplayBuffer:
    """Uniform ring buffer over flat numpy arrays (the reference's
    ``ReplayBuffer`` role, ``rllib/utils/replay_buffers/``)."""

    def __init__(self, capacity: int, obs_dim: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros(capacity, np.int32)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self.pos = 0
        self.size = 0

    def add_batch(self, obs, actions, rewards, next_obs, dones):
        for i in range(len(obs)):
            p = self.pos
            self.obs[p] = obs[i]
            self.next_obs[p] = next_obs[i]
            self.actions[p] = actions[i]
            self.rewards[p] = rewards[i]
            self.dones[p] = dones[i]
            self.pos = (p + 1) % self.capacity
            self.size = min(self.size + 1, self.capacity)

    def sample(self, rng, n: int) -> Dict[str, np.ndarray]:
        idx = rng.integers(0, self.size, n)
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "next_obs": self.next_obs[idx],
            "dones": self.dones[idx],
        }


class DQN(Algorithm):
    def __init__(self, config: DQNConfig):
        super().__init__(config)
        import jax
        import optax

        probe = make_env(config.env)
        spec = probe.spec
        # the MLP policy's pi head doubles as the Q head (logits == Q-values)
        self.params = init_mlp_policy(
            jax.random.PRNGKey(config.seed), spec.obs_dim, spec.num_actions,
            config.hidden,
        )
        self.target_params = self.params
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.envs = VectorEnv(config.env, config.num_envs_per_runner,
                              seed=config.seed)
        self._obs = self.envs.reset()
        self.buffer = _ReplayBuffer(config.buffer_size, spec.obs_dim)
        self._update = jax.jit(self._make_update())
        self._q_values = jax.jit(lambda p, o: apply_mlp_policy(p, o)[0])
        self._rng = np.random.default_rng(config.seed)
        self._timesteps = 0
        self._since_target_sync = 0
        self._episode_returns: List[float] = []
        self._running_returns = np.zeros(config.num_envs_per_runner, np.float32)

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        optimizer = self.optimizer

        def loss_fn(params, target_params, batch):
            q = apply_mlp_policy(params, batch["obs"])[0]
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), axis=1
            )[:, 0]
            q_next_target = apply_mlp_policy(target_params, batch["next_obs"])[0]
            if cfg.double_q:
                q_next_online = apply_mlp_policy(params, batch["next_obs"])[0]
                best = jnp.argmax(q_next_online, axis=1)
                q_next = jnp.take_along_axis(q_next_target, best[:, None], axis=1)[:, 0]
            else:
                q_next = jnp.max(q_next_target, axis=1)
            target = batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) * \
                jax.lax.stop_gradient(q_next)
            td = q_taken - target
            return jnp.mean(optax.huber_loss(td)), jnp.mean(jnp.abs(td))

        def update(params, target_params, opt_state, batch):
            (loss, td_abs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {"td_loss": loss, "td_abs": td_abs}

        return update

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._timesteps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final - cfg.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n_envs = cfg.num_envs_per_runner
        metrics: Dict[str, Any] = {}
        for _ in range(max(1, cfg.steps_per_iter // n_envs)):
            eps = self._epsilon()
            q = np.asarray(self._q_values(self.params, self._obs))
            actions = q.argmax(axis=1)
            explore = self._rng.random(n_envs) < eps
            actions = np.where(
                explore, self._rng.integers(0, q.shape[1], n_envs), actions
            )
            next_obs, rewards, dones = self.envs.step(actions)
            self.buffer.add_batch(self._obs, actions, rewards, next_obs, dones)
            self._running_returns += rewards
            for i, d in enumerate(dones):
                if d:
                    self._episode_returns.append(float(self._running_returns[i]))
                    self._running_returns[i] = 0.0
            self._obs = next_obs
            self._timesteps += n_envs
            self._since_target_sync += n_envs
        if self.buffer.size >= cfg.learning_starts:
            for _ in range(cfg.updates_per_iter):
                batch = self.buffer.sample(self._rng, cfg.train_batch_size)
                self.params, self.opt_state, metrics = self._update(
                    self.params, self.target_params, self.opt_state, batch
                )
            if self._since_target_sync >= cfg.target_update_freq:
                self.target_params = self.params
                self._since_target_sync = 0
        self._episode_returns = self._episode_returns[-100:]
        return {
            "episode_return_mean": float(np.mean(self._episode_returns))
            if self._episode_returns else 0.0,
            "num_env_steps_sampled_lifetime": self._timesteps,
            "epsilon": self._epsilon(),
            **{k: float(v) for k, v in metrics.items()},
        }

    def get_state(self):
        import jax

        return {
            "params": jax.tree.map(np.asarray, self.params),
            "target_params": jax.tree.map(np.asarray, self.target_params),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
            "timesteps": self._timesteps,
        }

    def set_state(self, state):
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.opt_state = state["opt_state"]
        self._timesteps = state["timesteps"]

    def stop(self):
        pass
