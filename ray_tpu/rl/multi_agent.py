"""Multi-agent RL: env contract, multi-agent episode collection, and
per-policy PPO learning.

Parity: ``rllib/env/multi_agent_env.py`` (the dict-keyed env API with the
``__all__`` termination sentinel), ``rllib/env/multi_agent_env_runner.py``
(episode collection with a policy-mapping function), and the multi-RLModule
learner (``rllib/core/rl_module/multi_rl_module.py``): each policy id owns
its own module (params + optimizer state); one jitted update is shared
across policies and applied per-policy to its own batch — the TPU-first
shape of per-policy learner updates.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import CartPoleEnv, make_env
from ray_tpu.rl.models import apply_mlp_policy, init_mlp_policy


class MultiAgentEnv:
    """The multi-agent env contract (parity: ``MultiAgentEnv``):

    * ``reset() -> (obs_dict, info_dict)`` keyed by agent id;
    * ``step(action_dict) -> (obs, rewards, terminateds, truncateds, infos)``
      where ``terminateds["__all__"]`` / ``truncateds["__all__"]`` end the
      episode. Agents absent from ``obs`` need no action next step.
    """

    agents: List[str] = []

    def reset(self, *, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError


class MultiAgentCartPole(MultiAgentEnv):
    """N independent CartPoles, one per agent (the reference's own
    multi-agent test env, ``rllib/examples/envs/classes/multi_agent/``).
    The episode ends when EVERY agent's pole has fallen (or time caps)."""

    def __init__(self, num_agents: int = 2, seed: Optional[int] = None):
        self.agents = [f"agent_{i}" for i in range(num_agents)]
        self._envs = {
            aid: CartPoleEnv(seed=None if seed is None else seed + i)
            for i, aid in enumerate(self.agents)
        }
        self.spec = CartPoleEnv.spec
        self._done: Dict[str, bool] = {}

    def reset(self, *, seed: Optional[int] = None):
        obs = {}
        for i, (aid, env) in enumerate(self._envs.items()):
            obs[aid], _ = env.reset(seed=None if seed is None else seed + i)
        self._done = {aid: False for aid in self.agents}
        return obs, {}

    def step(self, action_dict: Dict[str, Any]):
        obs, rewards, terms, truncs, infos = {}, {}, {}, {}, {}
        for aid, action in action_dict.items():
            if self._done.get(aid, True):
                continue
            o, r, term, trunc, info = self._envs[aid].step(int(action))
            rewards[aid] = r
            terms[aid] = term
            truncs[aid] = trunc
            infos[aid] = info
            if term or trunc:
                self._done[aid] = True
            else:
                obs[aid] = o
        all_done = all(self._done.values())
        terms["__all__"] = all_done and not any(truncs.values())
        truncs["__all__"] = all_done and any(truncs.values())
        return obs, rewards, terms, truncs, infos


class _MultiAgentEpisodeCollector:
    """Steps N multi-agent env copies, routing each agent through its
    policy (parity: ``multi_agent_env_runner.py`` episode collection)."""

    def __init__(self, env_creator, n_envs: int, policy_mapping_fn, seed: int):
        self._envs = [env_creator(seed=seed + i) for i in range(n_envs)]
        self._map = policy_mapping_fn
        self._obs = [e.reset(seed=seed + i)[0] for i, e in enumerate(self._envs)]
        self._returns = [dict() for _ in self._envs]
        self.completed_returns: Dict[str, List[float]] = {}

    def collect(self, act_fn, rollout_len: int) -> Dict[str, Dict[str, np.ndarray]]:
        """``act_fn(policy_id, obs_batch) -> (actions, logp, values)``.
        Returns per-policy batches of T-major transition arrays."""
        # per policy: lists of transition dicts
        steps: Dict[str, Dict[str, list]] = {}

        def bucket(pid):
            return steps.setdefault(
                pid,
                {
                    k: []
                    for k in (
                        "obs",
                        "actions",
                        "logp",
                        "values",
                        "rewards",
                        "dones",
                        "lanes",
                    )
                },
            )

        # stable integer id per (env_idx, agent_id) lane: the flat per-policy
        # stream interleaves lanes per timestep, and GAE must bootstrap each
        # transition from its OWN lane's successor, not the next array row
        lane_ids: Dict[Tuple[int, str], int] = {}

        for _ in range(rollout_len):
            # group live (env_idx, agent_id) pairs by policy
            by_policy: Dict[str, List[Tuple[int, str]]] = {}
            for ei, obs in enumerate(self._obs):
                for aid in obs:
                    by_policy.setdefault(self._map(aid), []).append((ei, aid))
            actions_per_env: List[Dict[str, int]] = [dict() for _ in self._envs]
            pending = {}  # (ei, aid) -> (pid, action, logp, value)
            for pid, pairs in by_policy.items():
                batch = np.stack([self._obs[ei][aid] for ei, aid in pairs])
                actions, logp, values = act_fn(pid, batch)
                for j, (ei, aid) in enumerate(pairs):
                    actions_per_env[ei][aid] = int(actions[j])
                    pending[(ei, aid)] = (pid, batch[j], int(actions[j]),
                                          float(logp[j]), float(values[j]))
            for ei, env in enumerate(self._envs):
                if not actions_per_env[ei]:
                    continue
                obs2, rewards, terms, truncs, _ = env.step(actions_per_env[ei])
                for aid, act in actions_per_env[ei].items():
                    pid, ob, a, lp, v = pending[(ei, aid)]
                    done = terms.get(aid, False) or truncs.get(aid, False)
                    b = bucket(pid)
                    b["obs"].append(ob)
                    b["actions"].append(a)
                    b["logp"].append(lp)
                    b["values"].append(v)
                    b["rewards"].append(rewards.get(aid, 0.0))
                    b["dones"].append(float(done))
                    b["lanes"].append(
                        lane_ids.setdefault((ei, aid), len(lane_ids))
                    )
                    ret = self._returns[ei]
                    ret[aid] = ret.get(aid, 0.0) + rewards.get(aid, 0.0)
                if terms.get("__all__") or truncs.get("__all__"):
                    for aid, total in self._returns[ei].items():
                        self.completed_returns.setdefault(
                            self._map(aid), []
                        ).append(total)
                    self._returns[ei] = {}
                    obs2, _ = env.reset()
                self._obs[ei] = obs2
        return {
            pid: {k: np.asarray(v, np.float32 if k != "actions" else np.int32)
                  for k, v in b.items()}
            for pid, b in steps.items()
        }


class MultiAgentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.entropy_coeff = 0.01
        self.vf_loss_coeff = 0.5
        self.gae_lambda = 0.95
        self.num_epochs = 8
        self.minibatch_size = 512
        self.grad_clip = 0.5
        self.policies: List[str] = []
        self.policy_mapping_fn: Callable[[str], str] = lambda aid: aid

    def multi_agent(
        self,
        policies: List[str],
        policy_mapping_fn: Optional[Callable[[str], str]] = None,
    ) -> "MultiAgentPPOConfig":
        """Parity: ``AlgorithmConfig.multi_agent(policies=...,
        policy_mapping_fn=...)``."""
        self.policies = list(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO(Algorithm):
    """PPO with one module per policy id (parity: MultiRLModule + the
    multi-agent learner path)."""

    def __init__(self, config: MultiAgentPPOConfig):
        super().__init__(config)
        import jax
        import optax

        if not config.policies:
            raise ValueError("use .multi_agent(policies=[...]) first")
        probe = make_env(config.env) if not callable(config.env) else config.env()
        spec = probe.spec
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip), optax.adam(config.lr)
        )
        # per-policy modules: independent params + optimizer state
        self.params: Dict[str, Any] = {}
        self.opt_states: Dict[str, Any] = {}
        for i, pid in enumerate(config.policies):
            p = init_mlp_policy(
                jax.random.PRNGKey(config.seed + i),
                spec.obs_dim,
                spec.num_actions,
                config.hidden,
            )
            self.params[pid] = p
            self.opt_states[pid] = self.optimizer.init(p)
        self._update = jax.jit(self._make_update())
        self._act = jax.jit(lambda p, o: apply_mlp_policy(p, o))
        def _create(seed=None):
            if callable(config.env):
                try:
                    return config.env(seed=seed)
                except TypeError:
                    return config.env()
            return make_env(config.env, seed=seed)

        self._collector = _MultiAgentEpisodeCollector(
            _create,
            config.num_envs_per_runner,
            config.policy_mapping_fn,
            config.seed,
        )
        self._rng = np.random.default_rng(config.seed)
        self._timesteps = 0

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        optimizer = self.optimizer

        def loss_fn(params, batch):
            logits, values = apply_mlp_policy(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=1
            )[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param) * adv,
            )
            pi_loss = -jnp.mean(surr)
            vf_loss = jnp.mean((values - batch["returns"]) ** 2)
            entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
            return pi_loss + cfg.vf_loss_coeff * vf_loss - cfg.entropy_coeff * entropy

        def update(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return update

    def _act_fn(self, pid: str, obs: np.ndarray):
        # pad to a power-of-two batch so jit compiles O(log n) programs, not
        # one per distinct live-agent count (agents die at arbitrary steps)
        n = len(obs)
        padded = 1 << (n - 1).bit_length() if n > 1 else 1
        if padded != n:
            obs = np.concatenate([obs, np.zeros((padded - n,) + obs.shape[1:], obs.dtype)])
        logits, values = self._act(self.params[pid], obs)
        logits = np.asarray(logits)[:n]
        values = np.asarray(values)[:n]
        # sample from the categorical policy
        u = self._rng.gumbel(size=logits.shape)
        actions = np.argmax(logits + u, axis=1)
        logp_all = logits - np.log(np.exp(logits).sum(axis=1, keepdims=True))
        logp = np.take_along_axis(logp_all, actions[:, None], axis=1)[:, 0]
        return actions, logp, np.asarray(values)

    def _gae_flat(self, b: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """Per-policy GAE over the flat transition stream. The stream
        interleaves (env, agent) lanes per timestep, so the backward pass
        runs PER LANE (lane ids carried by the collector): each transition
        bootstraps from its own lane's successor, with ``done`` as the
        episode boundary inside a lane. A single flat pass would compute
        deltas against unrelated agents' states (the reference computes GAE
        per episode, rllib/evaluation/postprocessing.py)."""
        cfg = self.config
        rewards, values, dones = b["rewards"], b["values"], b["dones"]
        lanes = b.get("lanes")
        n = len(rewards)
        adv = np.zeros(n, np.float32)
        lane_keys = (
            np.zeros(n, np.int32) if lanes is None else lanes.astype(np.int32)
        )
        for lane in np.unique(lane_keys):
            idx = np.nonzero(lane_keys == lane)[0]  # time-ordered
            last_adv = 0.0
            next_value = 0.0
            for t in idx[::-1]:
                nonterminal = 1.0 - dones[t]
                delta = rewards[t] + cfg.gamma * next_value * nonterminal - values[t]
                last_adv = delta + cfg.gamma * cfg.gae_lambda * nonterminal * last_adv
                adv[t] = last_adv
                next_value = values[t]
        returns = adv + values
        return {
            "obs": b["obs"],
            "actions": b["actions"].astype(np.int32),
            "logp_old": b["logp"],
            "advantages": (adv - adv.mean()) / (adv.std() + 1e-8),
            "returns": returns,
        }

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        per_policy = self._collector.collect(self._act_fn, cfg.rollout_len)
        metrics: Dict[str, Any] = {}
        for pid, raw in per_policy.items():
            batch = self._gae_flat(raw)
            n = len(batch["obs"])
            self._timesteps += n
            loss = 0.0
            mb = min(cfg.minibatch_size, 256)  # constant => ONE compiled update
            for _ in range(cfg.num_epochs):
                perm = self._rng.permutation(n)
                for start in range(0, n, mb):
                    idx = perm[start : start + mb]
                    if len(idx) < mb:
                        # pad the ragged tail with resampled rows so every
                        # minibatch shares the compiled shape
                        idx = np.concatenate(
                            [idx, self._rng.integers(0, n, mb - len(idx))]
                        )
                    mini = {k: v[idx] for k, v in batch.items()}
                    self.params[pid], self.opt_states[pid], loss = self._update(
                        self.params[pid], self.opt_states[pid], mini
                    )
            metrics[f"{pid}/loss"] = float(loss)
        returns_all: List[float] = []
        for pid, rets in self._collector.completed_returns.items():
            rets[:] = rets[-100:]
            if rets:
                metrics[f"{pid}/episode_return_mean"] = float(np.mean(rets))
                returns_all.extend(rets)
        metrics["episode_return_mean"] = (
            float(np.mean(returns_all)) if returns_all else 0.0
        )
        metrics["num_env_steps_sampled_lifetime"] = self._timesteps
        return metrics

    def get_state(self):
        import jax

        return {
            "params": {
                pid: jax.tree.map(np.asarray, p) for pid, p in self.params.items()
            },
            "timesteps": self._timesteps,
        }

    def set_state(self, state):
        self.params.update(state["params"])
        self._timesteps = state.get("timesteps", 0)

    def stop(self):
        pass
