"""APPO: asynchronous PPO — IMPALA's actor-learner architecture with the
PPO clipped-surrogate objective on V-trace-corrected advantages.

Parity: ``rllib/algorithms/appo/appo.py:1`` (APPO = IMPALA + surrogate
clipping, Espeholt et al. V-trace for the off-policy correction) and the
torch loss at ``rllib/algorithms/appo/torch/appo_torch_learner.py``. Same
TPU-first shape as IMPALA: the learner update is ONE jitted SPMD program over
a ``data``-axis mesh (in-process or spanning learner worker processes via
``jax.distributed``); only the loss differs, so APPO reuses the whole
IMPALA runner/learner plane through the update-builder registry.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.rl.impala import IMPALA, IMPALAConfig, vtrace_targets
from ray_tpu.rl.models import apply_mlp_policy


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        # RLlib APPO defaults: clip 0.4, lower LR than IMPALA
        self.clip_param = 0.4
        self.lr = 5e-4

    def build(self) -> "APPO":
        return APPO(self)


def build_appo_update(cfg_vals: Dict[str, Any], optimizer):
    """APPO learner update: V-trace targets + PPO clipped surrogate, where
    the importance ratio is pi/mu against the BEHAVIOR policy (async: the
    sampling policy lags the learner)."""
    import jax
    import jax.numpy as jnp
    import optax

    def loss_fn(params, batch):
        T, N = batch["actions"].shape
        obs = batch["obs"].reshape(T * N, -1)
        logits, values = apply_mlp_policy(params, obs)
        logits = logits.reshape(T, N, -1)
        values = values.reshape(T, N)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1
        )[..., 0]
        rhos = jnp.exp(logp - batch["logp"])  # pi / mu
        vs, pg_adv = vtrace_targets(
            values,
            batch["last_values"],
            batch["rewards"],
            batch["dones"],
            rhos,
            cfg_vals["gamma"],
            cfg_vals["vtrace_clip_rho"],
            cfg_vals["vtrace_clip_c"],
        )
        clip = cfg_vals["clip_param"]
        surrogate = jnp.minimum(
            rhos * pg_adv, jnp.clip(rhos, 1.0 - clip, 1.0 + clip) * pg_adv
        )
        w = batch["mask"][None, :]
        denom = jnp.maximum(jnp.sum(w) * T, 1.0)
        pg_loss = -jnp.sum(surrogate * w) / denom
        vf_loss = 0.5 * jnp.sum(((values - vs) ** 2) * w) / denom
        entropy = (
            -jnp.sum(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1) * w) / denom
        )
        loss = (
            pg_loss
            + cfg_vals["vf_loss_coeff"] * vf_loss
            - cfg_vals["entropy_coeff"] * entropy
        )
        return loss, {
            "pg_loss": pg_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }

    def update(params, opt_state, batch):
        grads, metrics = jax.grad(loss_fn, has_aux=True)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, metrics

    return update


class APPO(IMPALA):
    @classmethod
    def _update_builder_name(cls) -> str:
        return "appo"

    @classmethod
    def _extra_cfg_vals(cls, config) -> Dict[str, Any]:
        return {"clip_param": float(getattr(config, "clip_param", 0.4))}
