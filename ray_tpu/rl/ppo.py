"""PPO: clipped-surrogate policy optimization.

Parity: ``rllib/algorithms/ppo/`` — GAE advantages, clipped policy loss +
value loss + entropy bonus, minibatch epochs; learner update is one jitted
program (the torch-DDP learner group becomes SPMD over the mesh when learner
devices > 1). Learning target parity: CartPole-v1 return >= 150
(``rllib/tuned_examples/ppo/cartpole-ppo.yaml:5-7``).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import make_env
from ray_tpu.rl.env_runner import EnvRunnerGroup
from ray_tpu.rl.models import apply_mlp_policy, init_mlp_policy


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.entropy_coeff = 0.01
        self.vf_loss_coeff = 0.5
        self.gae_lambda = 0.95
        self.num_epochs = 8
        self.minibatch_size = 512
        self.grad_clip = 0.5

    def build(self) -> "PPO":
        return PPO(self)


class PPO(Algorithm):
    def __init__(self, config: PPOConfig):
        super().__init__(config)
        import jax
        import optax

        self._jax = jax
        probe = make_env(config.env)
        spec = probe.spec
        from ray_tpu.rl.env_runner import resolve_obs_dim

        obs_dim = resolve_obs_dim(config, spec)
        self.params = init_mlp_policy(
            jax.random.PRNGKey(config.seed), obs_dim, spec.num_actions, config.hidden
        )
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.grad_clip), optax.adam(config.lr)
        )
        self.opt_state = self.optimizer.init(self.params)
        self.runners = EnvRunnerGroup(
            config.env,
            config.num_env_runners,
            config.num_envs_per_runner,
            config.rollout_len,
            seed=config.seed,
            connectors=getattr(config, "env_to_module_connector", None),
        )
        self._update = jax.jit(self._make_update())
        self._recent_returns: List[float] = []
        self._timesteps = 0

    # -- loss/update -------------------------------------------------------

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        optimizer = self.optimizer

        def loss_fn(params, batch):
            logits, values = apply_mlp_policy(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, batch["actions"][:, None], axis=1)[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param) * adv,
            )
            pi_loss = -jnp.mean(surr)
            vf_loss = jnp.mean((values - batch["returns"]) ** 2)
            entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=1))
            total = pi_loss + cfg.vf_loss_coeff * vf_loss - cfg.entropy_coeff * entropy
            return total, (pi_loss, vf_loss, entropy)

        def update(params, opt_state, batch):
            (total, (pi_l, vf_l, ent)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {
                "total_loss": total,
                "policy_loss": pi_l,
                "vf_loss": vf_l,
                "entropy": ent,
            }

        return update

    # -- GAE ---------------------------------------------------------------

    def _gae(self, rollout) -> Dict[str, np.ndarray]:
        cfg = self.config
        rewards, values, dones = rollout["rewards"], rollout["values"], rollout["dones"]
        T, N = rewards.shape
        adv = np.zeros((T, N), np.float32)
        last_adv = np.zeros(N, np.float32)
        next_value = rollout["last_values"]
        for t in reversed(range(T)):
            nonterminal = 1.0 - dones[t].astype(np.float32)
            delta = rewards[t] + cfg.gamma * next_value * nonterminal - values[t]
            last_adv = delta + cfg.gamma * cfg.gae_lambda * nonterminal * last_adv
            adv[t] = last_adv
            next_value = values[t]
        returns = adv + values
        flat = lambda x: x.reshape(-1, *x.shape[2:])  # noqa: E731
        return {
            "obs": flat(rollout["obs"]),
            "actions": flat(rollout["actions"]),
            "logp_old": flat(rollout["logp"]),
            "advantages": flat(adv),
            "returns": flat(returns),
        }

    # -- training step -----------------------------------------------------

    def training_step(self) -> Dict[str, Any]:
        rollouts = self.runners.sample(self.params)
        batches = [self._gae(r) for r in rollouts]
        batch = {k: np.concatenate([b[k] for b in batches]) for k in batches[0]}
        adv = batch["advantages"]
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        n = len(batch["obs"])
        self._timesteps += n
        rng = np.random.default_rng(self.iteration)
        metrics = {}
        for _ in range(self.config.num_epochs):
            perm = rng.permutation(n)
            for start in range(0, n, self.config.minibatch_size):
                idx = perm[start : start + self.config.minibatch_size]
                mini = {k: v[idx] for k, v in batch.items()}
                self.params, self.opt_state, metrics = self._update(
                    self.params, self.opt_state, mini
                )
        for r in rollouts:
            self._recent_returns.extend(r["episode_returns"].tolist())
        self._recent_returns = self._recent_returns[-100:]
        mean_return = (
            float(np.mean(self._recent_returns)) if self._recent_returns else 0.0
        )
        return {
            "episode_return_mean": mean_return,
            "num_env_steps_sampled_lifetime": self._timesteps,
            **{k: float(v) for k, v in metrics.items()},
        }

    # -- state -------------------------------------------------------------

    def get_state(self):
        import jax

        return {
            "params": jax.tree.map(np.asarray, self.params),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
            "timesteps": self._timesteps,
        }

    def set_state(self, state):
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self._timesteps = state["timesteps"]

    def stop(self):
        self.runners.stop()
