"""Multi-process SPMD learner group.

Parity: ``rllib/core/learner/learner_group.py:154-174`` — N learner workers
updating one policy. TPU-first redesign: instead of N torch-DDP processes
exchanging NCCL allreduces, each learner worker (an actor, typically one per
host/slice) joins a ``jax.distributed`` coordination service; the update is
then ONE jitted SPMD program whose mesh spans every worker's devices — XLA
places the gradient reductions on ICI (gloo on the virtual-CPU test path).

Driver protocol per step: split the host batch into per-process shards along
the env axis and invoke ``update`` on every worker concurrently; the workers
gang-execute the program. Rank 0 returns metrics and (refreshed) host params
for the env runners.

Fault tolerance (parity: learner-group restart in
``train/_internal/backend_executor.py``): a worker death surfaces as a failed
``update`` round; :meth:`restart` tears the group down, re-rendezvous under a
fresh attempt-suffixed key, and restores the last known params.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu import exceptions as exc


@ray_tpu.remote
class SPMDLearnerWorker:
    """One learner process; rank 0 is the metrics/params endpoint."""

    def __init__(self, rank: int, world: int, rdzv_key: str, builder_config: dict):
        from ray_tpu._private.worker import get_runtime
        from ray_tpu.parallel import distributed as dist
        from ray_tpu.train.jax_utils import ensure_platform

        ensure_platform()
        self.rank, self.world = rank, world
        if world > 1:
            rt = get_runtime()
            coord = dist.rendezvous_via_kv(rt, rdzv_key, rank, world)
            dist.initialize(coord, num_processes=world, process_id=rank)
        self._build(builder_config)

    def _build(self, bc: dict) -> None:
        import jax
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ray_tpu.rl.impala import (
            impala_batch_shardings,
            resolve_update_builder,
        )
        from ray_tpu.rl.models import init_mlp_policy

        self._jax = jax
        devices = jax.devices()  # GLOBAL devices across all learner processes
        self._mesh = Mesh(np.array(devices), ("data",))
        replicated, batch_shardings = impala_batch_shardings(self._mesh)
        self._replicated = replicated
        self._batch_shardings = batch_shardings
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(bc["grad_clip"]), optax.adam(bc["lr"])
        )
        host_params = init_mlp_policy(
            jax.random.PRNGKey(bc["seed"]),
            bc["obs_dim"],
            bc["num_actions"],
            bc["hidden"],
        )
        if "init_params" in bc and bc["init_params"] is not None:
            host_params = bc["init_params"]
        self.params = self._replicate(host_params)
        host_opt = bc.get("init_opt_state")
        if host_opt is None:
            host_opt = self.optimizer.init(host_params)
        self.opt_state = self._replicate(host_opt)
        self._update = jax.jit(
            resolve_update_builder(bc.get("update_builder", "impala"))(
                bc["cfg_vals"], self.optimizer
            ),
            in_shardings=(replicated, replicated, batch_shardings),
            out_shardings=(replicated, replicated, replicated),
        )

    def _replicate(self, pytree):
        """Host pytree -> fully-replicated global arrays (every process
        supplies the identical full value)."""
        jax = self._jax

        def rep(x):
            return jax.make_array_from_process_local_data(
                self._replicated, np.asarray(x)
            )

        return jax.tree.map(rep, pytree)

    def _globalize_batch(self, local_batch: Dict[str, np.ndarray]):
        """Per-process shard -> global sharded arrays (env axis split across
        all learner processes)."""
        jax = self._jax
        out = {}
        for k, v in local_batch.items():
            out[k] = jax.make_array_from_process_local_data(
                self._batch_shardings[k], v
            )
        return out

    def update(self, local_batch: Dict[str, np.ndarray]):
        """One gang-executed SPMD step; all ranks must call concurrently."""
        batch = self._globalize_batch(local_batch)
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, batch
        )
        if self.rank != 0:
            return None
        host = {
            k: float(np.asarray(v.addressable_data(0)))
            for k, v in metrics.items()
        }
        return host, self.host_params()

    def host_params(self):
        jax = self._jax
        return jax.tree.map(
            lambda x: np.asarray(x.addressable_data(0)), self.params
        )

    def host_opt_state(self):
        jax = self._jax
        return jax.tree.map(
            lambda x: np.asarray(x.addressable_data(0)), self.opt_state
        )

    def set_params(self, host_params) -> None:
        self.params = self._replicate(host_params)

    def set_opt_state(self, host_opt_state) -> None:
        self.opt_state = self._replicate(host_opt_state)

    def ping(self) -> bool:
        return True

    def num_local_devices(self) -> int:
        return self._jax.local_device_count()

    def total_devices(self) -> int:
        return len(self._jax.devices())

    def shutdown(self) -> None:
        from ray_tpu.parallel import distributed as dist

        try:
            dist.shutdown()
        except Exception:
            pass


class SPMDLearnerGroup:
    """Driver-side handle to N gang-scheduled learner worker actors."""

    def __init__(
        self,
        num_workers: int,
        builder_config: dict,
        runtime_env: Optional[dict] = None,
        num_cpus_per_worker: float = 1.0,
        init_timeout_s: float = 300.0,
        update_timeout_s: float = 300.0,
    ):
        self.num_workers = num_workers
        self._builder_config = dict(builder_config)
        self._runtime_env = runtime_env
        self._num_cpus = num_cpus_per_worker
        self._init_timeout = init_timeout_s
        self._update_timeout = update_timeout_s
        self._attempt = 0
        self._params_cache = None
        self._opt_cache = None
        self.workers: List[Any] = []
        self.total_devices = 0
        self._start()

    def _start(self) -> None:
        key = f"rl_learners_{uuid.uuid4().hex[:8]}_a{self._attempt}"
        opts: Dict[str, Any] = {"num_cpus": self._num_cpus}
        if self._runtime_env:
            opts["runtime_env"] = self._runtime_env
        bc = dict(self._builder_config)
        bc["init_params"] = self._params_cache
        bc["init_opt_state"] = self._opt_cache
        self.workers = [
            SPMDLearnerWorker.options(**opts).remote(
                rank, self.num_workers, key, bc
            )
            for rank in range(self.num_workers)
        ]
        # barrier: every worker joined the coordination service and compiled
        counts = ray_tpu.get(
            [w.total_devices.remote() for w in self.workers],
            timeout=self._init_timeout,
        )
        assert len(set(counts)) == 1, f"device-count disagreement: {counts}"
        self.total_devices = counts[0]
        if self._params_cache is None:
            self._params_cache = ray_tpu.get(
                self.workers[0].host_params.remote(), timeout=self._init_timeout
            )

    def split(self, batch: Dict[str, np.ndarray]) -> List[Dict[str, np.ndarray]]:
        """Split the padded host batch into per-process contiguous shards
        along the env axis (matching the mesh's device order)."""
        world = self.num_workers
        shards: List[Dict[str, np.ndarray]] = [dict() for _ in range(world)]
        for k, v in batch.items():
            env_axis = 0 if k in ("last_values", "mask") else 1
            n = v.shape[env_axis]
            assert n % world == 0, f"{k}: env axis {n} not divisible by {world}"
            step = n // world
            for i in range(world):
                sl = [slice(None)] * v.ndim
                sl[env_axis] = slice(i * step, (i + 1) * step)
                shards[i][k] = v[tuple(sl)]
        return shards

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One SPMD step across the group; restarts the group on worker
        DEATH and retries once (the pre-batch params were restored, so
        re-feeding is not a double apply). A bare timeout first probes
        liveness: a slow-but-healthy gang gets one extended wait instead of
        a kill — killing it could discard an already-applied update and
        re-apply the batch."""
        shards = self.split(batch)
        refs = [w.update.remote(s) for w, s in zip(self.workers, shards)]
        try:
            out = ray_tpu.get(refs, timeout=self._update_timeout)
        except exc.GetTimeoutError:
            if self._all_alive():
                # healthy but slow (compile storm, loaded box): the update
                # may be mid-flight — wait it out rather than double-apply.
                # A second timeout means the gang is wedged, not slow:
                # restart and re-feed (documented at-least-once; optimizer
                # state is salvaged by restart()).
                try:
                    out = ray_tpu.get(refs, timeout=self._update_timeout)
                except exc.GetTimeoutError:
                    self.restart()
                    out = ray_tpu.get(
                        [w.update.remote(s) for w, s in zip(self.workers, shards)],
                        timeout=self._update_timeout,
                    )
            else:
                self.restart()
                out = ray_tpu.get(
                    [w.update.remote(s) for w, s in zip(self.workers, shards)],
                    timeout=self._update_timeout,
                )
        except (exc.ActorDiedError, exc.WorkerCrashedError, exc.TaskError):
            self.restart()
            out = ray_tpu.get(
                [w.update.remote(s) for w, s in zip(self.workers, shards)],
                timeout=self._update_timeout,
            )
        metrics, host_params = out[0]
        self._params_cache = host_params
        return metrics

    def _all_alive(self) -> bool:
        try:
            ray_tpu.get(
                [w.ping.remote() for w in self.workers], timeout=10.0
            )
            return True
        except Exception:
            return False

    def cached_params(self):
        return self._params_cache

    def set_params(self, host_params) -> None:
        self._params_cache = host_params
        ray_tpu.get(
            [w.set_params.remote(host_params) for w in self.workers],
            timeout=self._update_timeout,
        )

    def restart(self) -> None:
        """Kill every worker and rebuild the gang under a fresh rendezvous
        key, restoring the last known params (parity: backend_executor's
        worker-group restart). Optimizer state is salvaged from any
        surviving worker first, so a partial gang death doesn't silently
        reset Adam moments."""
        for w in self.workers:
            try:
                self._opt_cache = ray_tpu.get(
                    w.host_opt_state.remote(), timeout=10.0
                )
                break
            except Exception:
                continue
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self._attempt += 1
        self._start()

    def stop(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
