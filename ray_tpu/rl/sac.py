"""SAC (discrete actions): maximum-entropy off-policy RL.

Parity: ``rllib/algorithms/sac/`` — twin soft Q networks with polyak target
tracking, a stochastic (categorical) actor, and auto-tuned entropy
temperature alpha. Discrete-action formulation per the public soft
actor-critic literature (exact expectations over the action simplex instead
of the reparameterization trick). TPU-native: actor + both critics + alpha
update in ONE jitted program.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.dqn import _ReplayBuffer
from ray_tpu.rl.env import VectorEnv, make_env
from ray_tpu.rl.models import apply_mlp_policy, init_mlp_policy


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.alpha_lr = 3e-4
        self.buffer_size = 50_000
        self.learning_starts = 1_000
        self.train_batch_size = 128
        self.tau = 0.01  # polyak coefficient for target critics
        self.target_entropy_fraction = 0.7  # of max entropy log(|A|)
        self.initial_alpha = 0.2
        self.updates_per_iter = 64
        self.steps_per_iter = 512

    def build(self) -> "SAC":
        return SAC(self)


class SAC(Algorithm):
    def __init__(self, config: SACConfig):
        super().__init__(config)
        import jax
        import jax.numpy as jnp
        import optax

        probe = make_env(config.env)
        spec = probe.spec
        self._n_actions = spec.num_actions
        key = jax.random.PRNGKey(config.seed)
        k_actor, k_q1, k_q2 = jax.random.split(key, 3)
        # actor: logits head; critics: the pi head doubles as per-action Q
        self.actor = init_mlp_policy(k_actor, spec.obs_dim, spec.num_actions, config.hidden)
        self.q1 = init_mlp_policy(k_q1, spec.obs_dim, spec.num_actions, config.hidden)
        self.q2 = init_mlp_policy(k_q2, spec.obs_dim, spec.num_actions, config.hidden)
        self.q1_target = self.q1
        self.q2_target = self.q2
        self.log_alpha = jnp.log(jnp.asarray(config.initial_alpha, jnp.float32))
        self.actor_opt = optax.adam(config.lr)
        self.q_opt = optax.adam(config.lr)
        self.alpha_opt = optax.adam(config.alpha_lr)
        self.actor_state = self.actor_opt.init(self.actor)
        self.q1_state = self.q_opt.init(self.q1)
        self.q2_state = self.q_opt.init(self.q2)
        self.alpha_state = self.alpha_opt.init(self.log_alpha)
        self._target_entropy = config.target_entropy_fraction * float(
            np.log(spec.num_actions)
        )
        self._update = jax.jit(self._make_update())
        self._policy_logits = jax.jit(lambda p, o: apply_mlp_policy(p, o)[0])
        self.envs = VectorEnv(config.env, config.num_envs_per_runner, seed=config.seed)
        self._obs = self.envs.reset()
        self.buffer = _ReplayBuffer(config.buffer_size, spec.obs_dim)
        self._rng = np.random.default_rng(config.seed)
        self._timesteps = 0
        self._episode_returns: List[float] = []
        self._running_returns = np.zeros(config.num_envs_per_runner, np.float32)

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        target_entropy = self._target_entropy

        def pi_stats(actor, obs):
            logits = apply_mlp_policy(actor, obs)[0]
            logp = jax.nn.log_softmax(logits)
            return jnp.exp(logp), logp

        def q_loss_fn(q_params, target, batch):
            q = apply_mlp_policy(q_params, batch["obs"])[0]
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), axis=1
            )[:, 0]
            return jnp.mean((q_taken - target) ** 2)

        def actor_loss_fn(actor, q1, q2, alpha, obs):
            probs, logp = pi_stats(actor, obs)
            qmin = jnp.minimum(
                apply_mlp_policy(q1, obs)[0], apply_mlp_policy(q2, obs)[0]
            )
            # E_a~pi [ alpha*logpi - Q ], exact over the simplex
            loss = jnp.mean(jnp.sum(probs * (alpha * logp - qmin), axis=1))
            entropy = -jnp.mean(jnp.sum(probs * logp, axis=1))
            return loss, entropy

        def update(state, batch):
            (actor, q1, q2, q1_t, q2_t, log_alpha,
             actor_st, q1_st, q2_st, alpha_st) = state
            alpha = jnp.exp(log_alpha)
            # soft targets: r + gamma * E_a'~pi [ Qmin_target - alpha*logpi ]
            probs_next, logp_next = pi_stats(actor, batch["next_obs"])
            qmin_next = jnp.minimum(
                apply_mlp_policy(q1_t, batch["next_obs"])[0],
                apply_mlp_policy(q2_t, batch["next_obs"])[0],
            )
            v_next = jnp.sum(probs_next * (qmin_next - alpha * logp_next), axis=1)
            target = batch["rewards"] + cfg.gamma * (1.0 - batch["dones"]) * v_next
            target = jax.lax.stop_gradient(target)

            q1_l, q1_g = jax.value_and_grad(q_loss_fn)(q1, target, batch)
            q2_l, q2_g = jax.value_and_grad(q_loss_fn)(q2, target, batch)
            up1, q1_st = self.q_opt.update(q1_g, q1_st, q1)
            q1 = optax.apply_updates(q1, up1)
            up2, q2_st = self.q_opt.update(q2_g, q2_st, q2)
            q2 = optax.apply_updates(q2, up2)

            (a_l, entropy), a_g = jax.value_and_grad(actor_loss_fn, has_aux=True)(
                actor, q1, q2, alpha, batch["obs"]
            )
            upa, actor_st = self.actor_opt.update(a_g, actor_st, actor)
            actor = optax.apply_updates(actor, upa)

            # temperature: drive entropy toward the target
            def alpha_loss_fn(log_a):
                return jnp.exp(log_a) * jax.lax.stop_gradient(
                    entropy - target_entropy
                )

            al_l, al_g = jax.value_and_grad(alpha_loss_fn)(log_alpha)
            upal, alpha_st = self.alpha_opt.update(al_g, alpha_st, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, upal)

            # polyak-track the target critics
            q1_t = jax.tree.map(lambda t, s: (1 - cfg.tau) * t + cfg.tau * s, q1_t, q1)
            q2_t = jax.tree.map(lambda t, s: (1 - cfg.tau) * t + cfg.tau * s, q2_t, q2)
            new_state = (actor, q1, q2, q1_t, q2_t, log_alpha,
                         actor_st, q1_st, q2_st, alpha_st)
            metrics = {
                "q1_loss": q1_l,
                "q2_loss": q2_l,
                "actor_loss": a_l,
                "entropy": entropy,
                "alpha": alpha,
            }
            return new_state, metrics

        return update

    def _state_tuple(self):
        return (self.actor, self.q1, self.q2, self.q1_target, self.q2_target,
                self.log_alpha, self.actor_state, self.q1_state, self.q2_state,
                self.alpha_state)

    def _set_state_tuple(self, s):
        (self.actor, self.q1, self.q2, self.q1_target, self.q2_target,
         self.log_alpha, self.actor_state, self.q1_state, self.q2_state,
         self.alpha_state) = s

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n_envs = cfg.num_envs_per_runner
        metrics: Dict[str, Any] = {}
        for _ in range(max(1, cfg.steps_per_iter // n_envs)):
            logits = np.asarray(self._policy_logits(self.actor, self._obs))
            u = self._rng.gumbel(size=logits.shape)
            actions = np.argmax(logits + u, axis=1)  # sample from pi
            next_obs, rewards, dones = self.envs.step(actions)
            self.buffer.add_batch(self._obs, actions, rewards, next_obs, dones)
            self._running_returns += rewards
            for i, d in enumerate(dones):
                if d:
                    self._episode_returns.append(float(self._running_returns[i]))
                    self._running_returns[i] = 0.0
            self._obs = next_obs
            self._timesteps += n_envs
        if self.buffer.size >= cfg.learning_starts:
            state = self._state_tuple()
            for _ in range(cfg.updates_per_iter):
                batch = self.buffer.sample(self._rng, cfg.train_batch_size)
                state, metrics = self._update(state, batch)
            self._set_state_tuple(state)
        self._episode_returns = self._episode_returns[-100:]
        return {
            "episode_return_mean": float(np.mean(self._episode_returns))
            if self._episode_returns else 0.0,
            "num_env_steps_sampled_lifetime": self._timesteps,
            **{k: float(v) for k, v in metrics.items()},
        }

    def get_state(self):
        import jax

        return {
            "actor": jax.tree.map(np.asarray, self.actor),
            "q1": jax.tree.map(np.asarray, self.q1),
            "q2": jax.tree.map(np.asarray, self.q2),
            "log_alpha": np.asarray(self.log_alpha),
            "timesteps": self._timesteps,
        }

    def set_state(self, state):
        self.actor = state["actor"]
        self.q1 = state["q1"]
        self.q2 = state["q2"]
        self.q1_target = state["q1"]
        self.q2_target = state["q2"]
        self.log_alpha = state["log_alpha"]
        self._timesteps = state.get("timesteps", 0)

    def stop(self):
        pass
