"""Offline RL: behavior cloning (BC) and advantage-weighted MARWIL.

Parity: ``rllib/algorithms/bc/`` and ``rllib/algorithms/marwil/`` — train a
policy from a fixed dataset of (obs, action[, reward]) with no environment
interaction, reading batches through the framework's Data library exactly as
the reference reads offline JSON samples through Ray Data (``rllib/offline/``).
The update is one jitted program; MARWIL weights log-likelihood by
exp(beta * advantage) with a moving value baseline.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rl.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rl.env import make_env
from ray_tpu.rl.models import apply_mlp_policy, init_mlp_policy


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.train_batch_size = 256
        self.beta = 0.0  # 0 => pure BC; >0 => MARWIL advantage weighting
        self.vf_coeff = 1.0
        self.dataset = None  # ray_tpu.data.Dataset with obs/actions[/returns]

    def offline_data(self, dataset) -> "BCConfig":
        self.dataset = dataset
        return self

    def build(self) -> "BC":
        return BC(self)


class MARWILConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0

    def build(self) -> "MARWIL":
        return MARWIL(self)


class BC(Algorithm):
    def __init__(self, config: BCConfig):
        super().__init__(config)
        import jax
        import optax

        if config.dataset is None:
            raise ValueError("BCConfig.offline_data(dataset) is required")
        probe = make_env(config.env)
        spec = probe.spec
        self.params = init_mlp_policy(
            jax.random.PRNGKey(config.seed), spec.obs_dim, spec.num_actions,
            config.hidden,
        )
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._update = jax.jit(self._make_update())
        # materialize once; offline data is read-mostly
        self._data = config.dataset.materialize()
        self._epoch_iter = None
        self._samples = 0

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        optimizer = self.optimizer

        def loss_fn(params, batch):
            logits, values = apply_mlp_policy(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None].astype(jnp.int32), axis=1
            )[:, 0]
            if cfg.beta > 0.0:
                adv = batch["returns"] - values
                weight = jnp.exp(cfg.beta * jax.lax.stop_gradient(
                    adv / (jnp.std(adv) + 1e-8)))
                pi_loss = -jnp.mean(weight * logp)
                vf_loss = jnp.mean(adv ** 2)
                return pi_loss + cfg.vf_coeff * vf_loss, pi_loss
            pi_loss = -jnp.mean(logp)
            return pi_loss, pi_loss

        def update(params, opt_state, batch):
            (total, pi_l), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, batch
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {"total_loss": total, "policy_loss": pi_l}

        return update

    def _next_batch(self) -> Dict[str, np.ndarray]:
        if self._epoch_iter is None:
            self._epoch_iter = self._data.iter_batches(
                batch_size=self.config.train_batch_size, drop_last=True
            )
        try:
            batch = next(self._epoch_iter)
        except StopIteration:
            self._epoch_iter = self._data.iter_batches(
                batch_size=self.config.train_batch_size, drop_last=True
            )
            try:
                batch = next(self._epoch_iter)
            except StopIteration:
                raise ValueError(
                    f"offline dataset has fewer rows than train_batch_size="
                    f"{self.config.train_batch_size}"
                ) from None
        out = {"obs": np.asarray(batch["obs"], np.float32),
               "actions": np.asarray(batch["actions"], np.int32)}
        if self.config.beta > 0.0:
            out["returns"] = np.asarray(batch["returns"], np.float32)
        return out

    def training_step(self) -> Dict[str, Any]:
        metrics = {}
        for _ in range(16):
            batch = self._next_batch()
            self.params, self.opt_state, metrics = self._update(
                self.params, self.opt_state, batch
            )
            self._samples += len(batch["obs"])
        return {
            "num_samples_trained": self._samples,
            **{k: float(v) for k, v in metrics.items()},
        }

    def evaluate(self, num_episodes: int = 10, seed: int = 0) -> float:
        """Greedy rollout return in the real env (parity: evaluation workers)."""
        import jax

        returns = []
        for ep in range(num_episodes):
            env = make_env(self.config.env, seed=seed + ep)
            obs, _ = env.reset()
            total, done = 0.0, False
            while not done:
                logits, _ = apply_mlp_policy(self.params, obs[None])
                obs, r, term, trunc, _ = env.step(int(np.argmax(logits[0])))
                total += r
                done = term or trunc
            returns.append(total)
        return float(np.mean(returns))

    def get_state(self):
        import jax

        return {"params": jax.tree.map(np.asarray, self.params),
                "opt_state": jax.tree.map(np.asarray, self.opt_state),
                "samples": self._samples}

    def set_state(self, state):
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self._samples = state["samples"]

    def stop(self):
        pass


class MARWIL(BC):
    pass


class CQLConfig(BCConfig):
    """Conservative Q-Learning on a fixed dataset (parity:
    ``rllib/algorithms/cql/``, Kumar et al. 2020 — discrete CQL(H))."""

    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.cql_alpha = 1.0  # conservative-regularizer weight
        self.tau = 0.01  # target-network Polyak rate (applied every step)

    def build(self) -> "CQL":
        return CQL(self)


class CQL(Algorithm):
    """Discrete CQL: double-Q TD learning plus the conservative penalty
    ``logsumexp_a Q(s,a) - Q(s, a_data)`` that pushes down out-of-dataset
    action values — the core of ``rllib/algorithms/cql``. The offline
    dataset provides (obs, actions, rewards, next_obs, dones) rows read
    through the Data library, and the update is one jitted program."""

    def __init__(self, config: CQLConfig):
        super().__init__(config)
        import jax
        import optax

        if config.dataset is None:
            raise ValueError("CQLConfig.offline_data(dataset) is required")
        probe = make_env(config.env)
        spec = probe.spec
        # the policy MLP's logits head doubles as Q(s, .) (same trick DQN
        # uses); value head unused
        self.params = init_mlp_policy(
            jax.random.PRNGKey(config.seed), spec.obs_dim, spec.num_actions,
            config.hidden,
        )
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.optimizer = optax.adam(config.lr)
        self.opt_state = self.optimizer.init(self.params)
        self._update = jax.jit(self._make_update())
        self._data = config.dataset.materialize()
        self._epoch_iter = None
        self._samples = 0
        self._steps = 0

    def _make_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config
        optimizer = self.optimizer

        def loss_fn(params, target_params, batch):
            q_all = apply_mlp_policy(params, batch["obs"])[0]
            q_data = jnp.take_along_axis(
                q_all, batch["actions"][:, None].astype(jnp.int32), axis=1
            )[:, 0]
            q_next = apply_mlp_policy(target_params, batch["next_obs"])[0]
            target = batch["rewards"] + cfg.gamma * (
                1.0 - batch["dones"]
            ) * jnp.max(q_next, axis=1)
            td_loss = jnp.mean(
                (q_data - jax.lax.stop_gradient(target)) ** 2
            )
            # CQL(H): minimize logsumexp over ALL actions, maximize the
            # dataset action's value — out-of-distribution actions are
            # pushed below the data support
            cql_term = jnp.mean(
                jax.scipy.special.logsumexp(q_all, axis=1) - q_data
            )
            return td_loss + cfg.cql_alpha * cql_term, (td_loss, cql_term)

        def update(params, target_params, opt_state, batch):
            (total, (td, cql)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, target_params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target_params = jax.tree.map(
                lambda t, p: (1.0 - cfg.tau) * t + cfg.tau * p,
                target_params,
                params,
            )
            return params, target_params, opt_state, {
                "total_loss": total,
                "td_loss": td,
                "cql_loss": cql,
            }

        return update

    def _next_batch(self) -> Dict[str, np.ndarray]:
        if self._epoch_iter is None:
            self._epoch_iter = self._data.iter_batches(
                batch_size=self.config.train_batch_size, drop_last=True
            )
        try:
            batch = next(self._epoch_iter)
        except StopIteration:
            self._epoch_iter = self._data.iter_batches(
                batch_size=self.config.train_batch_size, drop_last=True
            )
            batch = next(self._epoch_iter)
        return {
            "obs": np.asarray(batch["obs"], np.float32),
            "actions": np.asarray(batch["actions"], np.int32),
            "rewards": np.asarray(batch["rewards"], np.float32),
            "next_obs": np.asarray(batch["next_obs"], np.float32),
            "dones": np.asarray(batch["dones"], np.float32),
        }

    def training_step(self) -> Dict[str, Any]:
        metrics = {}
        for _ in range(16):
            batch = self._next_batch()
            self.params, self.target_params, self.opt_state, metrics = (
                self._update(
                    self.params, self.target_params, self.opt_state, batch
                )
            )
            self._samples += len(batch["obs"])
            self._steps += 1
        return {
            "num_samples_trained": self._samples,
            **{k: float(v) for k, v in metrics.items()},
        }

    def evaluate(self, num_episodes: int = 10, seed: int = 0) -> float:
        returns = []
        for ep in range(num_episodes):
            env = make_env(self.config.env, seed=seed + ep)
            obs, _ = env.reset()
            total, done = 0.0, False
            while not done:
                q, _ = apply_mlp_policy(self.params, obs[None])
                obs, r, term, trunc, _ = env.step(int(np.argmax(q[0])))
                total += r
                done = term or trunc
            returns.append(total)
        return float(np.mean(returns))

    def get_state(self):
        import jax

        return {
            "params": jax.tree.map(np.asarray, self.params),
            "target_params": jax.tree.map(np.asarray, self.target_params),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
            "samples": self._samples,
        }

    def set_state(self, state):
        self.params = state["params"]
        self.target_params = state["target_params"]
        self.opt_state = state["opt_state"]
        self._samples = state["samples"]

    def stop(self):
        pass
