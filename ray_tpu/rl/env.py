"""Environment API and built-in envs.

Parity: RLlib's gymnasium-based env layer; the API matches gymnasium
(``reset() -> (obs, info)``, ``step() -> (obs, reward, terminated, truncated,
info)``) so user gym envs drop in. CartPole dynamics follow the classic
control formulation (public standard: Barto, Sutton & Anderson 1983) so the
reference's tuned-example learning thresholds are comparable.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class EnvSpec:
    def __init__(self, obs_dim: int, num_actions: int, max_episode_steps: int):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.max_episode_steps = max_episode_steps


class CartPoleEnv:
    """CartPole-v1-compatible: pole balancing, +1 reward/step, 500-step cap."""

    spec = EnvSpec(obs_dim=4, num_actions=2, max_episode_steps=500)

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * math.pi / 360
    X_LIMIT = 2.4

    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)
        self._state = None
        self._steps = 0

    def reset(self, *, seed: Optional[int] = None) -> Tuple[np.ndarray, dict]:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._steps = 0
        return self._state.astype(np.float32), {}

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        costh, sinth = math.cos(theta), math.sin(theta)
        total_mass = self.CART_MASS + self.POLE_MASS
        pml = self.POLE_MASS * self.POLE_HALF_LEN
        temp = (force + pml * theta_dot**2 * sinth) / total_mass
        theta_acc = (self.GRAVITY * sinth - costh * temp) / (
            self.POLE_HALF_LEN * (4.0 / 3.0 - self.POLE_MASS * costh**2 / total_mass)
        )
        x_acc = temp - pml * theta_acc * costh / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot])
        self._steps += 1
        terminated = bool(
            x < -self.X_LIMIT
            or x > self.X_LIMIT
            or theta < -self.THETA_LIMIT
            or theta > self.THETA_LIMIT
        )
        truncated = self._steps >= self.spec.max_episode_steps
        return self._state.astype(np.float32), 1.0, terminated, truncated, {}


_REGISTRY: Dict[str, Callable[..., Any]] = {
    "CartPole-v1": CartPoleEnv,
}


def register_env(name: str, creator: Callable[..., Any]) -> None:
    """Parity: ``ray.tune.registry.register_env``."""
    _REGISTRY[name] = creator


def make_env(name_or_creator, seed: Optional[int] = None):
    if callable(name_or_creator):
        return name_or_creator()
    creator = _REGISTRY.get(name_or_creator)
    if creator is None:
        raise ValueError(
            f"unknown env '{name_or_creator}'; register it with rl.register_env"
        )
    try:
        return creator(seed=seed)
    except TypeError:
        return creator()


class VectorEnv:
    """N independent env copies stepped in lockstep with auto-reset."""

    def __init__(self, creator, n: int, seed: int = 0):
        self.envs = [make_env(creator, seed=seed + i) for i in range(n)]
        self.n = n

    def reset(self) -> np.ndarray:
        return np.stack([e.reset()[0] for e in self.envs])

    def step(self, actions: np.ndarray):
        obs, rewards, dones = [], [], []
        for e, a in zip(self.envs, actions):
            o, r, term, trunc, _ = e.step(int(a))
            done = term or trunc
            if done:
                o = e.reset()[0]
            obs.append(o)
            rewards.append(r)
            dones.append(done)
        return np.stack(obs), np.array(rewards, np.float32), np.array(dones)
