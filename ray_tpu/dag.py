"""Lazy DAG API + compiled execution.

Parity: ``python/ray/dag`` — ``.bind()`` builds ``FunctionNode`` /
``ClassNode`` / ``ClassMethodNode`` / ``InputNode`` graphs (``dag_node.py``),
``.execute()`` walks them; ``experimental_compile`` returns a ``CompiledDAG``
(``compiled_dag_node.py:391``).

TPU-native compiled path: where the reference lowers compiled DAGs to mutable
plasma channels + NCCL p2p, stages that are pure jax functions fuse into ONE
jitted XLA program (``compile_jax_pipeline``) so inter-stage edges become
in-program values on-device — the aDAG analogue described in SURVEY.md §2.3.
Non-fusable (stateful-actor) stages run as pre-planned actor calls with the
object store carrying edges.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu


class DAGNode:
    def execute(self, *input_args, **input_kwargs):
        return _execute(self, input_args, input_kwargs, {})

    def experimental_compile(self, buffer_size_bytes: int = 4 * 1024 * 1024):
        """Compile for repeated execution (parity:
        ``compiled_dag_node.py:391``). Actor-method graphs — linear chains,
        branches, diamonds, multi-output — lower to resident stage loops
        connected by channels: mutable shared-memory channels between
        same-node stages (``shared_memory_channel.py:88`` analogue), and
        authenticated one-slot socket channels for cross-node edges (the
        reference's cross-node mutable-object forwarding). Graphs that are
        not pure actor-method DAGs keep the pre-planned actor-call path."""
        chain = _linear_actor_chain(self)
        if chain is not None:
            return ChannelCompiledDAG(chain, buffer_size_bytes)
        plan = _general_actor_graph(self)
        if plan is not None:
            return GeneralCompiledDAG(plan, buffer_size_bytes)
        return CompiledDAG(self)


class InputNode(DAGNode):
    """Placeholder for the value supplied at ``execute()`` time."""

    def __init__(self, index: int = 0):
        self.index = index

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key):
        self.parent = parent
        self.key = key


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        self.fn = remote_fn
        self.args = args
        self.kwargs = kwargs


class ClassNode(DAGNode):
    """A bound actor constructor; instantiated once per executing DAG."""

    def __init__(self, actor_cls, args, kwargs):
        self.actor_cls = actor_cls
        self.args = args
        self.kwargs = kwargs

    def bind_method(self, name):
        raise AttributeError(name)

    def __getattr__(self, name):
        if name.startswith("_") or name in ("actor_cls", "args", "kwargs"):
            raise AttributeError(name)

        class _M:
            def __init__(_s, node, method):
                _s.node = node
                _s.method = method

            def bind(_s, *args, **kwargs):
                return BoundClassMethodNode(_s.node, _s.method, args, kwargs)

        return _M(self, name)


class BoundClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        self.class_node = class_node
        self.method = method
        self.args = args
        self.kwargs = kwargs


class ClassMethodNode(DAGNode):
    """Method bind on an existing actor handle."""

    def __init__(self, handle, method: str, args, kwargs):
        self.handle = handle
        self.method = method
        self.args = args
        self.kwargs = kwargs


class MultiOutputNode(DAGNode):
    """Marks several DAG leaves as the outputs of one execution (parity:
    ``ray.dag.MultiOutputNode``); ``execute()``/compiled results are lists."""

    def __init__(self, outputs):
        self.outputs = list(outputs)


def _execute(node, input_args, input_kwargs, memo: Dict[int, Any]):
    """Post-order walk; returns an ObjectRef (or plain value for inputs)."""
    if id(node) in memo:
        return memo[id(node)]

    def rec(v):
        if isinstance(v, DAGNode):
            return _execute(v, input_args, input_kwargs, memo)
        return v

    if isinstance(node, InputNode):
        result = input_args[node.index] if input_args else None
    elif isinstance(node, InputAttributeNode):
        base = rec(node.parent)
        if isinstance(base, ray_tpu.ObjectRef):
            base = ray_tpu.get(base)
        result = base[node.key]
    elif isinstance(node, FunctionNode):
        args = [rec(a) for a in node.args]
        kwargs = {k: rec(v) for k, v in node.kwargs.items()}
        result = node.fn.remote(*args, **kwargs)
    elif isinstance(node, ClassNode):
        args = [rec(a) for a in node.args]
        kwargs = {k: rec(v) for k, v in node.kwargs.items()}
        result = node.actor_cls.remote(*args, **kwargs)
    elif isinstance(node, BoundClassMethodNode):
        handle = rec(node.class_node)
        args = [rec(a) for a in node.args]
        kwargs = {k: rec(v) for k, v in node.kwargs.items()}
        result = getattr(handle, node.method).remote(*args, **kwargs)
    elif isinstance(node, ClassMethodNode):
        args = [rec(a) for a in node.args]
        kwargs = {k: rec(v) for k, v in node.kwargs.items()}
        result = getattr(node.handle, node.method).remote(*args, **kwargs)
    elif isinstance(node, MultiOutputNode):
        result = [rec(o) for o in node.outputs]
    else:
        raise TypeError(f"unknown DAG node {type(node)}")
    memo[id(node)] = result
    return result


class CompiledDAG:
    """Pre-planned execution: actors in the graph are instantiated once and
    reused across ``execute()`` calls (the reference's compiled DAGs likewise
    pin actors + channels; here edges ride the object store)."""

    def __init__(self, output_node: DAGNode):
        self.output = output_node
        self._actor_cache: Dict[int, Any] = {}
        self._instantiate_actors(output_node)

    def _instantiate_actors(self, node):
        if isinstance(node, ClassNode) and id(node) not in self._actor_cache:
            args = [a for a in node.args if not isinstance(a, DAGNode)]
            kwargs = {k: v for k, v in node.kwargs.items() if not isinstance(v, DAGNode)}
            self._actor_cache[id(node)] = node.actor_cls.remote(*args, **kwargs)
        for child in _children(node):
            self._instantiate_actors(child)

    def execute(self, *input_args, **input_kwargs):
        memo = {nid: handle for nid, handle in self._actor_cache.items()}
        return _execute(self.output, input_args, input_kwargs, memo)

    def teardown(self):
        for handle in self._actor_cache.values():
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass


def _linear_actor_chain(output: DAGNode):
    """Detect InputNode -> m1(actor1) -> m2(actor2) -> ... chains.

    Returns [(class_node, method_name), ...] outermost-last, or None."""
    stages = []
    node = output
    while isinstance(node, BoundClassMethodNode):
        dag_args = [a for a in node.args if isinstance(a, DAGNode)]
        if len(node.args) != 1 or len(dag_args) != 1 or node.kwargs:
            return None
        stages.append((node.class_node, node.method))
        node = node.args[0]
    if not isinstance(node, InputNode) or not stages:
        return None
    # a ClassNode appearing in several stages must share ONE instance
    # (interpreted-execute semantics); the channel lowering spawns one
    # resident actor per stage, so bail to the actor-call path instead
    if len({id(cn) for cn, _ in stages}) != len(stages):
        return None
    return list(reversed(stages))


@ray_tpu.remote
class _PipelineStage:
    """Resident compiled-DAG stage: constructs the user class once, then
    loops channel-read -> method -> channel-write until the input closes."""

    def __init__(self, cls_blob: bytes, args, kwargs):
        import cloudpickle

        cls = cloudpickle.loads(cls_blob)
        self._inst = cls(*args, **kwargs)

    def run_loop(self, in_path, out_path, method, capacity):
        from ray_tpu.experimental.channel import Channel, ChannelClosedError

        in_ch = Channel(in_path, capacity)
        out_ch = Channel(out_path, capacity)
        fn = getattr(self._inst, method)
        while True:
            try:
                x = in_ch.read(timeout=None)
            except ChannelClosedError:
                out_ch.close()
                return
            if isinstance(x, _DagError):
                payload = x  # upstream failure: forward it downstream
            else:
                try:
                    payload = fn(x)
                except Exception as e:  # noqa: BLE001
                    import traceback

                    payload = _DagError(f"{e!r}\n{traceback.format_exc()}")
            try:
                # block until the reader consumes — a slow consumer must
                # backpressure the pipeline, not kill the resident loop
                out_ch.write(payload, timeout=None)
            except ChannelClosedError:
                return


class _DagError:
    """Stage failure riding the channel to the caller (parity: compiled DAGs
    propagate exceptions through the channel)."""

    def __init__(self, message: str):
        self.message = message


class _SeqBufferedResults:
    """FIFO result protocol shared by the channel-compiled DAGs: results
    arrive on the output channel(s) in execution order; out-of-order
    consumption buffers other executions' values per sequence number.
    Subclasses implement ``_read_one(timeout)``."""

    def _init_seq_state(self):
        self._closed = False
        self._next_seq = 0
        self._next_read = 0
        self._buffered: Dict[int, Any] = {}

    def _result_for(self, seq: int, timeout: float):
        if seq in self._buffered:
            return self._buffered.pop(seq)
        import time as _time

        deadline = _time.monotonic() + timeout
        while self._next_read <= seq:
            remaining = max(0.0, deadline - _time.monotonic())
            value = self._read_one(remaining)
            got = self._next_read
            self._next_read += 1
            if got == seq:
                return value
            self._buffered[got] = value
        return self._buffered.pop(seq)


class CompiledDAGRef:
    """Result handle of one compiled execution (parity: ``CompiledDAGRef``).

    Results are delivered in execution order on one channel; the owning DAG
    buffers out-of-order consumption so each ref gets ITS execution's value."""

    def __init__(self, dag: "ChannelCompiledDAG", seq: int, timeout: float):
        self._dag = dag
        self._seq = seq
        self._timeout = timeout

    def get(self, timeout: Optional[float] = None):
        value = self._dag._result_for(
            self._seq, self._timeout if timeout is None else timeout
        )
        err = None
        if isinstance(value, _DagError):
            err = value
        elif isinstance(value, list):
            err = next((v for v in value if isinstance(v, _DagError)), None)
        if err is not None:
            raise RuntimeError(f"compiled DAG stage failed: {err.message}")
        return value


class ChannelCompiledDAG(_SeqBufferedResults):
    """Linear actor pipeline lowered onto mutable shm channels."""

    def __init__(self, stages, capacity: int):
        import os
        import uuid

        import cloudpickle

        from ray_tpu._private.worker import get_driver
        from ray_tpu.experimental.channel import Channel

        drv = get_driver()
        base = (
            os.path.join(drv.node.shm_dir, "channels")
            if drv is not None and hasattr(drv, "node")
            else "/tmp/ray_tpu_channels"
        )
        tag = uuid.uuid4().hex[:8]
        n = len(stages)
        self._paths = [os.path.join(base, f"{tag}_{i}") for i in range(n + 1)]
        self._channels = [Channel(p, capacity, create=True) for p in self._paths]
        self._actors = []
        self._loops = []
        for i, (class_node, method) in enumerate(stages):
            args = [a for a in class_node.args if not isinstance(a, DAGNode)]
            kwargs = {
                k: v for k, v in class_node.kwargs.items() if not isinstance(v, DAGNode)
            }
            blob = cloudpickle.dumps(class_node.actor_cls._cls)
            actor = _PipelineStage.remote(blob, args, kwargs)
            self._actors.append(actor)
            self._loops.append(
                actor.run_loop.remote(
                    self._paths[i], self._paths[i + 1], method, capacity
                )
            )
        self._init_seq_state()

    def execute(self, value, timeout: float = 60.0) -> CompiledDAGRef:
        if self._closed:
            raise RuntimeError("compiled DAG is torn down")
        self._channels[0].write(value)
        ref = CompiledDAGRef(self, self._next_seq, timeout)
        self._next_seq += 1
        return ref

    def _read_one(self, timeout: float):
        return self._channels[-1].read(timeout=timeout)

    def teardown(self):
        if self._closed:
            return
        self._closed = True
        for ch in self._channels:
            ch.close()
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        for ch in self._channels:
            ch.release()
        import os

        for p in self._paths:
            try:
                os.unlink(p)
            except OSError:
                pass

    def __del__(self):
        # a dropped DAG must not leak resident stage actors (their loops
        # never finish on their own, so out-of-scope reaping can't fire)
        try:
            self.teardown()
        except Exception:
            pass


def _general_actor_graph(output: DAGNode):
    """Validate + plan an arbitrary actor-method DAG for channel lowering.

    Supported nodes: BoundClassMethodNode (constant kwargs; args may mix
    constants with DAG edges), InputNode / InputAttributeNode sources, and a
    MultiOutputNode root. Returns a plan dict or None (caller falls back to
    the pre-planned actor-call path). Parity: the reference compiles exactly
    these graphs in ``compiled_dag_node.py:391``.
    """
    roots = output.outputs if isinstance(output, MultiOutputNode) else [output]
    if not roots or not all(isinstance(r, BoundClassMethodNode) for r in roots):
        return None

    method_nodes: List[BoundClassMethodNode] = []  # topo (producers first)
    seen: Dict[int, bool] = {}

    def visit(node) -> bool:
        if isinstance(node, (InputNode, InputAttributeNode)):
            if isinstance(node, InputAttributeNode):
                node = node.parent
            # channel executions carry ONE input value; a multi-positional
            # InputNode(index>0) would silently get the wrong argument here,
            # so those graphs keep the interpreted path
            if not isinstance(node, InputNode) or node.index != 0:
                return False
            return True
        if not isinstance(node, BoundClassMethodNode):
            return False
        if id(node) in seen:
            return seen[id(node)]
        seen[id(node)] = True  # provisional (cycles are impossible in DAGs)
        if any(isinstance(v, DAGNode) for v in node.kwargs.values()):
            seen[id(node)] = False
            return False
        if not all(
            visit(a) for a in node.args if isinstance(a, DAGNode)
        ):
            seen[id(node)] = False
            return False
        # every stage needs at least one channel input: an all-constant
        # method would loop eagerly, decoupled from execute() pacing
        if not any(isinstance(a, DAGNode) for a in node.args):
            seen[id(node)] = False
            return False
        # class construction args must be constants (one instance per
        # class_node, built once at compile time)
        cn = node.class_node
        if any(isinstance(a, DAGNode) for a in cn.args) or any(
            isinstance(v, DAGNode) for v in cn.kwargs.values()
        ):
            seen[id(node)] = False
            return False
        method_nodes.append(node)
        return True

    if not all(visit(r) for r in roots):
        return None
    if not method_nodes:
        return None
    return {"roots": roots, "method_nodes": method_nodes}


class _EdgeHole:
    """Compile-time marker for a channel-fed argument position. A dedicated
    class (not an in-band tuple) so user constants can never collide."""

    def __init__(self, index: int):
        self.index = index


@ray_tpu.remote
class _GeneralStage:
    """Resident stage hosting ONE user-class instance and one channel loop
    per bound method node (threads via max_concurrency)."""

    def __init__(self, cls_blob: bytes, args, kwargs):
        import cloudpickle
        import threading

        cls = cloudpickle.loads(cls_blob)
        self._inst = cls(*args, **kwargs)
        self._writers: Dict[str, Any] = {}
        # several method loops share one instance; user method bodies run
        # one at a time, like any other actor (interpreted semantics)
        self._inst_lock = threading.Lock()

    def node_shm(self):
        from ray_tpu.experimental.channel import node_shm_dir

        return node_shm_dir()

    def prepare(self, out_edges, capacity: int):
        """Create writer endpoints for this stage's output edges.
        ``out_edges`` = [(edge_id, kind)]; returns {edge_id: reader_spec}."""
        from ray_tpu._private.worker import get_runtime
        from ray_tpu.experimental.channel import create_writer, node_shm_dir

        cfg = get_runtime().config
        key = (cfg.cluster_auth_key or "local").encode()
        specs = {}
        for edge_id, kind in out_edges:
            w, spec = create_writer(
                kind, edge_id, key, capacity,
                shm_dir=node_shm_dir(), host=cfg.cluster_host,
            )
            self._writers[edge_id] = w
            specs[edge_id] = spec
        return specs

    def run_method_loop(
        self,
        method: str,
        arg_template: List,  # constants, with _EdgeHole(i) holes
        kwargs: Dict,
        in_specs: List,      # reader specs, one per hole, in hole order
        out_edge_ids: List[str],
        capacity: int,
    ):
        from ray_tpu._private.worker import get_runtime
        from ray_tpu.experimental.channel import (
            ChannelClosedError,
            open_reader,
        )

        cfg = get_runtime().config
        key = (cfg.cluster_auth_key or "local").encode()
        readers = [open_reader(s, key, capacity) for s in in_specs]
        writers = [self._writers[eid] for eid in out_edge_ids]
        fn = getattr(self._inst, method)
        while True:
            try:
                vals = [r.read(timeout=None) for r in readers]
            except ChannelClosedError:
                for w in writers:
                    w.close()
                return
            err = next((v for v in vals if isinstance(v, _DagError)), None)
            if err is not None:
                payload = err  # upstream failure: forward it downstream
            else:
                args = [
                    vals[a.index] if isinstance(a, _EdgeHole) else a
                    for a in arg_template
                ]
                try:
                    with self._inst_lock:
                        payload = fn(*args, **kwargs)
                except Exception as e:  # noqa: BLE001
                    import traceback

                    payload = _DagError(f"{e!r}\n{traceback.format_exc()}")
            try:
                for w in writers:
                    w.write(payload, timeout=None)
            except ChannelClosedError:
                return


class GeneralCompiledDAG(_SeqBufferedResults):
    """Arbitrary actor-method DAG lowered onto channels: shm between
    same-node stages, authenticated sockets across nodes. One resident
    actor per ClassNode; one loop thread per bound method."""

    def __init__(self, plan: Dict, capacity: int):
        import uuid

        import cloudpickle

        from ray_tpu._private.worker import get_runtime
        from ray_tpu.experimental.channel import (
            create_writer,
            node_shm_dir,
            open_reader,
        )

        cfg = get_runtime().config
        self._auth = (cfg.cluster_auth_key or "local").encode()
        self._capacity = capacity
        roots = plan["roots"]
        method_nodes = plan["method_nodes"]
        tag = uuid.uuid4().hex[:8]

        # one resident actor per ClassNode (methods on one class_node share
        # the instance; each method loop needs its own thread)
        loops_per_class: Dict[int, int] = {}
        for m in method_nodes:
            loops_per_class[id(m.class_node)] = (
                loops_per_class.get(id(m.class_node), 0) + 1
            )
        self._actors: Dict[int, Any] = {}
        for m in method_nodes:
            cid = id(m.class_node)
            if cid not in self._actors:
                cn = m.class_node
                user_opts = {
                    k: cn.actor_cls._options[k]
                    for k in cn.actor_cls._explicit
                    if k in ("num_cpus", "num_tpus", "resources",
                             "scheduling_strategy")
                }
                self._actors[cid] = _GeneralStage.options(
                    max_concurrency=loops_per_class[cid] + 1, **user_opts
                ).remote(
                    cloudpickle.dumps(cn.actor_cls._cls), cn.args, cn.kwargs
                )

        # locate every endpoint (same shm dir == same node == shm channel)
        shm_of = {
            cid: shm
            for cid, shm in zip(
                self._actors,
                ray_tpu.get(
                    [a.node_shm.remote() for a in self._actors.values()],
                    timeout=120,
                ),
            )
        }
        driver_shm = node_shm_dir()

        def loc(end) -> Optional[str]:
            return driver_shm if end == "driver" else shm_of[end]

        # edges: producer -> (consumer, arg position). Input edges carry an
        # optional attribute key resolved driver-side at write time.
        edges: List[Dict] = []
        in_holes: Dict[int, List] = {id(m): [] for m in method_nodes}
        for m in method_nodes:
            for a in m.args:
                if isinstance(a, InputNode):
                    src, edge_key = "driver", None
                elif isinstance(a, InputAttributeNode):
                    src, edge_key = "driver", a.key
                elif isinstance(a, BoundClassMethodNode):
                    src, edge_key = id(a.class_node), None
                else:
                    continue
                eid = f"{tag}_{len(edges)}"
                edge = {
                    "id": eid,
                    "src": src,
                    "src_node": a if src != "driver" else None,
                    "dst": id(m.class_node),
                    "key": edge_key,
                }
                edges.append(edge)
                in_holes[id(m)].append(edge)
        root_edges: List[Dict] = []
        for r in roots:
            eid = f"{tag}_{len(edges) + len(root_edges)}r"
            root_edges.append(
                {"id": eid, "src": id(r.class_node), "src_node": r,
                 "dst": "driver", "key": None}
            )

        def kind_of(edge) -> str:
            a, b = loc(edge["src"]), loc(edge["dst"])
            return "shm" if a is not None and a == b else "sock"

        # writer creation: group stage-produced edges by producing method
        # node (its loop owns the writer ends)
        produced: Dict[int, List[Dict]] = {}
        for e in edges + root_edges:
            if e["src"] == "driver":
                continue
            produced.setdefault(id(e["src_node"]), []).append(e)
        specs: Dict[str, Any] = {}
        for m in method_nodes:
            mine = produced.get(id(m), [])
            if mine:
                got = ray_tpu.get(
                    self._actors[id(m.class_node)].prepare.remote(
                        [(e["id"], kind_of(e)) for e in mine], capacity
                    ),
                    timeout=120,
                )
                specs.update(got)
        # driver-produced input edges
        self._input_writers: List = []
        for e in edges:
            if e["src"] != "driver":
                continue
            w, spec = create_writer(
                kind_of(e), e["id"], self._auth, capacity,
                shm_dir=driver_shm, host=cfg.cluster_host,
            )
            self._input_writers.append((w, e["key"]))
            specs[e["id"]] = spec

        # start one loop per method node
        self._loops = []
        for m in method_nodes:
            holes = in_holes[id(m)]
            template: List = []
            hole_i = 0
            for a in m.args:
                if isinstance(
                    a, (InputNode, InputAttributeNode, BoundClassMethodNode)
                ):
                    template.append(_EdgeHole(hole_i))
                    hole_i += 1
                else:
                    template.append(a)
            self._loops.append(
                self._actors[id(m.class_node)].run_method_loop.remote(
                    m.method,
                    template,
                    dict(m.kwargs),
                    [specs[e["id"]] for e in holes],
                    [e["id"] for e in produced.get(id(m), [])],
                    capacity,
                )
            )
        # driver-side readers for the root edges — opened LAZILY on the
        # first result read: a socket reader's auth handshake only completes
        # when the writing stage accepts (at its first write, i.e. after an
        # execute()), so opening here would deadlock compile for any
        # cross-node output stage
        self._out_specs = [specs[e["id"]] for e in root_edges]
        self._out_readers: Optional[List] = None
        self._multi = len(self._out_specs) > 1
        # every shm edge path, for unlink at teardown (stage-created shm
        # files live in this node's shm dir only when the stage is local,
        # so unlink is best-effort per path)
        self._shm_paths = [
            spec[1] for spec in specs.values() if spec[0] == "shm"
        ]
        self._broken = False
        self._init_seq_state()

    def execute(self, value, timeout: float = 60.0) -> CompiledDAGRef:
        if self._closed:
            raise RuntimeError("compiled DAG is torn down")
        if self._broken:
            raise RuntimeError(
                "compiled DAG is in an inconsistent state after a partial "
                "write/read timeout; teardown() and recompile"
            )
        for i, (w, key) in enumerate(self._input_writers):
            try:
                w.write(value if key is None else value[key], timeout=timeout)
            except Exception:
                if i > 0:
                    # some inputs carry this execution and some don't: the
                    # stages are now out of step — refuse further use
                    self._broken = True
                raise
        ref = CompiledDAGRef(self, self._next_seq, timeout)
        self._next_seq += 1
        return ref

    def _read_one(self, timeout: float):
        import time as _time

        if self._out_readers is None:
            from ray_tpu.experimental.channel import open_reader

            self._out_readers = [
                open_reader(s, self._auth, self._capacity)
                for s in self._out_specs
            ]
        deadline = _time.monotonic() + timeout
        vals = []
        for i, r in enumerate(self._out_readers):
            try:
                vals.append(
                    r.read(timeout=max(0.0, deadline - _time.monotonic()))
                )
            except Exception:
                if i > 0:
                    # earlier outputs of this execution were consumed; the
                    # channels are desynchronized — refuse further use
                    self._broken = True
                raise
        return vals if self._multi else vals[0]

    def _result_for(self, seq: int, timeout: float):
        if self._broken:
            raise RuntimeError(
                "compiled DAG is in an inconsistent state after a partial "
                "write/read timeout; teardown() and recompile"
            )
        return super()._result_for(seq, timeout)

    def teardown(self):
        if self._closed:
            return
        self._closed = True
        for w, _ in self._input_writers:
            try:
                w.close()
            except Exception:
                pass
        for a in self._actors.values():
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        for r in self._out_readers or []:
            try:
                r.close()
            except Exception:
                pass
        import os as _os

        for p in self._shm_paths:
            try:
                _os.unlink(p)
            except OSError:
                pass

    def __del__(self):
        # a dropped DAG must not leak resident stage actors (their loops
        # never finish on their own, so out-of-scope reaping can't fire)
        try:
            self.teardown()
        except Exception:
            pass


def _children(node) -> List[DAGNode]:
    out = []
    for attr in ("args", "kwargs", "class_node", "parent", "outputs"):
        v = getattr(node, attr, None)
        if isinstance(v, DAGNode):
            out.append(v)
        elif isinstance(v, (list, tuple)):
            out.extend(x for x in v if isinstance(x, DAGNode))
        elif isinstance(v, dict):
            out.extend(x for x in v.values() if isinstance(x, DAGNode))
    return out


def compile_jax_pipeline(stages, donate: bool = False):
    """Fuse a chain of pure-jax stage functions into one jitted program.

    The TPU-native compiled-DAG fast path: stage boundaries become in-program
    values (XLA schedules/overlaps them; on a sharded mesh the edges lower to
    ICI transfers), instead of host round-trips through the object store.
    """
    import jax

    def fused(x):
        for stage in stages:
            x = stage(x)
        return x

    return jax.jit(fused, donate_argnums=(0,) if donate else ())
