"""Lazy DAG API + compiled execution.

Parity: ``python/ray/dag`` — ``.bind()`` builds ``FunctionNode`` /
``ClassNode`` / ``ClassMethodNode`` / ``InputNode`` graphs (``dag_node.py``),
``.execute()`` walks them; ``experimental_compile`` returns a ``CompiledDAG``
(``compiled_dag_node.py:391``).

TPU-native compiled path: where the reference lowers compiled DAGs to mutable
plasma channels + NCCL p2p, stages that are pure jax functions fuse into ONE
jitted XLA program (``compile_jax_pipeline``) so inter-stage edges become
in-program values on-device — the aDAG analogue described in SURVEY.md §2.3.
Non-fusable (stateful-actor) stages run as pre-planned actor calls with the
object store carrying edges.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu


class DAGNode:
    def execute(self, *input_args, **input_kwargs):
        return _execute(self, input_args, input_kwargs, {})

    def experimental_compile(self, buffer_size_bytes: int = 4 * 1024 * 1024):
        """Compile for repeated execution. Linear actor pipelines lower to
        mutable shared-memory channels — each stage runs a resident loop
        reading its input channel and writing the next, with no per-hop RPC
        or store allocation (the aDAG fast path,
        ``compiled_dag_node.py:391`` + ``shared_memory_channel.py:88``).
        Non-linear graphs keep the pre-planned actor-call path."""
        chain = _linear_actor_chain(self)
        if chain is not None:
            return ChannelCompiledDAG(chain, buffer_size_bytes)
        return CompiledDAG(self)


class InputNode(DAGNode):
    """Placeholder for the value supplied at ``execute()`` time."""

    def __init__(self, index: int = 0):
        self.index = index

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key):
        self.parent = parent
        self.key = key


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        self.fn = remote_fn
        self.args = args
        self.kwargs = kwargs


class ClassNode(DAGNode):
    """A bound actor constructor; instantiated once per executing DAG."""

    def __init__(self, actor_cls, args, kwargs):
        self.actor_cls = actor_cls
        self.args = args
        self.kwargs = kwargs

    def bind_method(self, name):
        raise AttributeError(name)

    def __getattr__(self, name):
        if name.startswith("_") or name in ("actor_cls", "args", "kwargs"):
            raise AttributeError(name)

        class _M:
            def __init__(_s, node, method):
                _s.node = node
                _s.method = method

            def bind(_s, *args, **kwargs):
                return BoundClassMethodNode(_s.node, _s.method, args, kwargs)

        return _M(self, name)


class BoundClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        self.class_node = class_node
        self.method = method
        self.args = args
        self.kwargs = kwargs


class ClassMethodNode(DAGNode):
    """Method bind on an existing actor handle."""

    def __init__(self, handle, method: str, args, kwargs):
        self.handle = handle
        self.method = method
        self.args = args
        self.kwargs = kwargs


def _execute(node, input_args, input_kwargs, memo: Dict[int, Any]):
    """Post-order walk; returns an ObjectRef (or plain value for inputs)."""
    if id(node) in memo:
        return memo[id(node)]

    def rec(v):
        if isinstance(v, DAGNode):
            return _execute(v, input_args, input_kwargs, memo)
        return v

    if isinstance(node, InputNode):
        result = input_args[node.index] if input_args else None
    elif isinstance(node, InputAttributeNode):
        base = rec(node.parent)
        if isinstance(base, ray_tpu.ObjectRef):
            base = ray_tpu.get(base)
        result = base[node.key]
    elif isinstance(node, FunctionNode):
        args = [rec(a) for a in node.args]
        kwargs = {k: rec(v) for k, v in node.kwargs.items()}
        result = node.fn.remote(*args, **kwargs)
    elif isinstance(node, ClassNode):
        args = [rec(a) for a in node.args]
        kwargs = {k: rec(v) for k, v in node.kwargs.items()}
        result = node.actor_cls.remote(*args, **kwargs)
    elif isinstance(node, BoundClassMethodNode):
        handle = rec(node.class_node)
        args = [rec(a) for a in node.args]
        kwargs = {k: rec(v) for k, v in node.kwargs.items()}
        result = getattr(handle, node.method).remote(*args, **kwargs)
    elif isinstance(node, ClassMethodNode):
        args = [rec(a) for a in node.args]
        kwargs = {k: rec(v) for k, v in node.kwargs.items()}
        result = getattr(node.handle, node.method).remote(*args, **kwargs)
    else:
        raise TypeError(f"unknown DAG node {type(node)}")
    memo[id(node)] = result
    return result


class CompiledDAG:
    """Pre-planned execution: actors in the graph are instantiated once and
    reused across ``execute()`` calls (the reference's compiled DAGs likewise
    pin actors + channels; here edges ride the object store)."""

    def __init__(self, output_node: DAGNode):
        self.output = output_node
        self._actor_cache: Dict[int, Any] = {}
        self._instantiate_actors(output_node)

    def _instantiate_actors(self, node):
        if isinstance(node, ClassNode) and id(node) not in self._actor_cache:
            args = [a for a in node.args if not isinstance(a, DAGNode)]
            kwargs = {k: v for k, v in node.kwargs.items() if not isinstance(v, DAGNode)}
            self._actor_cache[id(node)] = node.actor_cls.remote(*args, **kwargs)
        for child in _children(node):
            self._instantiate_actors(child)

    def execute(self, *input_args, **input_kwargs):
        memo = {nid: handle for nid, handle in self._actor_cache.items()}
        return _execute(self.output, input_args, input_kwargs, memo)

    def teardown(self):
        for handle in self._actor_cache.values():
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass


def _linear_actor_chain(output: DAGNode):
    """Detect InputNode -> m1(actor1) -> m2(actor2) -> ... chains.

    Returns [(class_node, method_name), ...] outermost-last, or None."""
    stages = []
    node = output
    while isinstance(node, BoundClassMethodNode):
        dag_args = [a for a in node.args if isinstance(a, DAGNode)]
        if len(node.args) != 1 or len(dag_args) != 1 or node.kwargs:
            return None
        stages.append((node.class_node, node.method))
        node = node.args[0]
    if not isinstance(node, InputNode) or not stages:
        return None
    # a ClassNode appearing in several stages must share ONE instance
    # (interpreted-execute semantics); the channel lowering spawns one
    # resident actor per stage, so bail to the actor-call path instead
    if len({id(cn) for cn, _ in stages}) != len(stages):
        return None
    return list(reversed(stages))


@ray_tpu.remote
class _PipelineStage:
    """Resident compiled-DAG stage: constructs the user class once, then
    loops channel-read -> method -> channel-write until the input closes."""

    def __init__(self, cls_blob: bytes, args, kwargs):
        import cloudpickle

        cls = cloudpickle.loads(cls_blob)
        self._inst = cls(*args, **kwargs)

    def run_loop(self, in_path, out_path, method, capacity):
        from ray_tpu.experimental.channel import Channel, ChannelClosedError

        in_ch = Channel(in_path, capacity)
        out_ch = Channel(out_path, capacity)
        fn = getattr(self._inst, method)
        while True:
            try:
                x = in_ch.read(timeout=None)
            except ChannelClosedError:
                out_ch.close()
                return
            if isinstance(x, _DagError):
                payload = x  # upstream failure: forward it downstream
            else:
                try:
                    payload = fn(x)
                except Exception as e:  # noqa: BLE001
                    import traceback

                    payload = _DagError(f"{e!r}\n{traceback.format_exc()}")
            try:
                # block until the reader consumes — a slow consumer must
                # backpressure the pipeline, not kill the resident loop
                out_ch.write(payload, timeout=None)
            except ChannelClosedError:
                return


class _DagError:
    """Stage failure riding the channel to the caller (parity: compiled DAGs
    propagate exceptions through the channel)."""

    def __init__(self, message: str):
        self.message = message


class CompiledDAGRef:
    """Result handle of one compiled execution (parity: ``CompiledDAGRef``).

    Results are delivered in execution order on one channel; the owning DAG
    buffers out-of-order consumption so each ref gets ITS execution's value."""

    def __init__(self, dag: "ChannelCompiledDAG", seq: int, timeout: float):
        self._dag = dag
        self._seq = seq
        self._timeout = timeout

    def get(self, timeout: Optional[float] = None):
        value = self._dag._result_for(
            self._seq, self._timeout if timeout is None else timeout
        )
        if isinstance(value, _DagError):
            raise RuntimeError(f"compiled DAG stage failed: {value.message}")
        return value


class ChannelCompiledDAG:
    """Linear actor pipeline lowered onto mutable shm channels."""

    def __init__(self, stages, capacity: int):
        import os
        import uuid

        import cloudpickle

        from ray_tpu._private.worker import get_driver
        from ray_tpu.experimental.channel import Channel

        drv = get_driver()
        base = (
            os.path.join(drv.node.shm_dir, "channels")
            if drv is not None and hasattr(drv, "node")
            else "/tmp/ray_tpu_channels"
        )
        tag = uuid.uuid4().hex[:8]
        n = len(stages)
        self._paths = [os.path.join(base, f"{tag}_{i}") for i in range(n + 1)]
        self._channels = [Channel(p, capacity, create=True) for p in self._paths]
        self._actors = []
        self._loops = []
        for i, (class_node, method) in enumerate(stages):
            args = [a for a in class_node.args if not isinstance(a, DAGNode)]
            kwargs = {
                k: v for k, v in class_node.kwargs.items() if not isinstance(v, DAGNode)
            }
            blob = cloudpickle.dumps(class_node.actor_cls._cls)
            actor = _PipelineStage.remote(blob, args, kwargs)
            self._actors.append(actor)
            self._loops.append(
                actor.run_loop.remote(
                    self._paths[i], self._paths[i + 1], method, capacity
                )
            )
        self._closed = False
        self._next_seq = 0
        self._next_read = 0
        self._buffered: Dict[int, Any] = {}

    def execute(self, value, timeout: float = 60.0) -> CompiledDAGRef:
        if self._closed:
            raise RuntimeError("compiled DAG is torn down")
        self._channels[0].write(value)
        ref = CompiledDAGRef(self, self._next_seq, timeout)
        self._next_seq += 1
        return ref

    def _result_for(self, seq: int, timeout: float):
        """Read results in FIFO channel order, buffering others, until this
        execution's value arrives."""
        if seq in self._buffered:
            return self._buffered.pop(seq)
        import time as _time

        deadline = _time.monotonic() + timeout
        while self._next_read <= seq:
            remaining = max(0.0, deadline - _time.monotonic())
            value = self._channels[-1].read(timeout=remaining)
            got = self._next_read
            self._next_read += 1
            if got == seq:
                return value
            self._buffered[got] = value
        return self._buffered.pop(seq)

    def teardown(self):
        if self._closed:
            return
        self._closed = True
        for ch in self._channels:
            ch.close()
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        for ch in self._channels:
            ch.release()
        import os

        for p in self._paths:
            try:
                os.unlink(p)
            except OSError:
                pass


def _children(node) -> List[DAGNode]:
    out = []
    for attr in ("args", "kwargs", "class_node", "parent"):
        v = getattr(node, attr, None)
        if isinstance(v, DAGNode):
            out.append(v)
        elif isinstance(v, (list, tuple)):
            out.extend(x for x in v if isinstance(x, DAGNode))
        elif isinstance(v, dict):
            out.extend(x for x in v.values() if isinstance(x, DAGNode))
    return out


def compile_jax_pipeline(stages, donate: bool = False):
    """Fuse a chain of pure-jax stage functions into one jitted program.

    The TPU-native compiled-DAG fast path: stage boundaries become in-program
    values (XLA schedules/overlaps them; on a sharded mesh the edges lower to
    ICI transfers), instead of host round-trips through the object store.
    """
    import jax

    def fused(x):
        for stage in stages:
            x = stage(x)
        return x

    return jax.jit(fused, donate_argnums=(0,) if donate else ())
