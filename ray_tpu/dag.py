"""Lazy DAG API + compiled execution.

Parity: ``python/ray/dag`` — ``.bind()`` builds ``FunctionNode`` /
``ClassNode`` / ``ClassMethodNode`` / ``InputNode`` graphs (``dag_node.py``),
``.execute()`` walks them; ``experimental_compile`` returns a ``CompiledDAG``
(``compiled_dag_node.py:391``).

TPU-native compiled path: where the reference lowers compiled DAGs to mutable
plasma channels + NCCL p2p, stages that are pure jax functions fuse into ONE
jitted XLA program (``compile_jax_pipeline``) so inter-stage edges become
in-program values on-device — the aDAG analogue described in SURVEY.md §2.3.
Non-fusable (stateful-actor) stages run as pre-planned actor calls with the
object store carrying edges.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import ray_tpu


class DAGNode:
    def execute(self, *input_args, **input_kwargs):
        return _execute(self, input_args, input_kwargs, {})

    def experimental_compile(self) -> "CompiledDAG":
        return CompiledDAG(self)


class InputNode(DAGNode):
    """Placeholder for the value supplied at ``execute()`` time."""

    def __init__(self, index: int = 0):
        self.index = index

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class InputAttributeNode(DAGNode):
    def __init__(self, parent: InputNode, key):
        self.parent = parent
        self.key = key


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        self.fn = remote_fn
        self.args = args
        self.kwargs = kwargs


class ClassNode(DAGNode):
    """A bound actor constructor; instantiated once per executing DAG."""

    def __init__(self, actor_cls, args, kwargs):
        self.actor_cls = actor_cls
        self.args = args
        self.kwargs = kwargs

    def bind_method(self, name):
        raise AttributeError(name)

    def __getattr__(self, name):
        if name.startswith("_") or name in ("actor_cls", "args", "kwargs"):
            raise AttributeError(name)

        class _M:
            def __init__(_s, node, method):
                _s.node = node
                _s.method = method

            def bind(_s, *args, **kwargs):
                return BoundClassMethodNode(_s.node, _s.method, args, kwargs)

        return _M(self, name)


class BoundClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        self.class_node = class_node
        self.method = method
        self.args = args
        self.kwargs = kwargs


class ClassMethodNode(DAGNode):
    """Method bind on an existing actor handle."""

    def __init__(self, handle, method: str, args, kwargs):
        self.handle = handle
        self.method = method
        self.args = args
        self.kwargs = kwargs


def _execute(node, input_args, input_kwargs, memo: Dict[int, Any]):
    """Post-order walk; returns an ObjectRef (or plain value for inputs)."""
    if id(node) in memo:
        return memo[id(node)]

    def rec(v):
        if isinstance(v, DAGNode):
            return _execute(v, input_args, input_kwargs, memo)
        return v

    if isinstance(node, InputNode):
        result = input_args[node.index] if input_args else None
    elif isinstance(node, InputAttributeNode):
        base = rec(node.parent)
        if isinstance(base, ray_tpu.ObjectRef):
            base = ray_tpu.get(base)
        result = base[node.key]
    elif isinstance(node, FunctionNode):
        args = [rec(a) for a in node.args]
        kwargs = {k: rec(v) for k, v in node.kwargs.items()}
        result = node.fn.remote(*args, **kwargs)
    elif isinstance(node, ClassNode):
        args = [rec(a) for a in node.args]
        kwargs = {k: rec(v) for k, v in node.kwargs.items()}
        result = node.actor_cls.remote(*args, **kwargs)
    elif isinstance(node, BoundClassMethodNode):
        handle = rec(node.class_node)
        args = [rec(a) for a in node.args]
        kwargs = {k: rec(v) for k, v in node.kwargs.items()}
        result = getattr(handle, node.method).remote(*args, **kwargs)
    elif isinstance(node, ClassMethodNode):
        args = [rec(a) for a in node.args]
        kwargs = {k: rec(v) for k, v in node.kwargs.items()}
        result = getattr(node.handle, node.method).remote(*args, **kwargs)
    else:
        raise TypeError(f"unknown DAG node {type(node)}")
    memo[id(node)] = result
    return result


class CompiledDAG:
    """Pre-planned execution: actors in the graph are instantiated once and
    reused across ``execute()`` calls (the reference's compiled DAGs likewise
    pin actors + channels; here edges ride the object store)."""

    def __init__(self, output_node: DAGNode):
        self.output = output_node
        self._actor_cache: Dict[int, Any] = {}
        self._instantiate_actors(output_node)

    def _instantiate_actors(self, node):
        if isinstance(node, ClassNode) and id(node) not in self._actor_cache:
            args = [a for a in node.args if not isinstance(a, DAGNode)]
            kwargs = {k: v for k, v in node.kwargs.items() if not isinstance(v, DAGNode)}
            self._actor_cache[id(node)] = node.actor_cls.remote(*args, **kwargs)
        for child in _children(node):
            self._instantiate_actors(child)

    def execute(self, *input_args, **input_kwargs):
        memo = {nid: handle for nid, handle in self._actor_cache.items()}
        return _execute(self.output, input_args, input_kwargs, memo)

    def teardown(self):
        for handle in self._actor_cache.values():
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass


def _children(node) -> List[DAGNode]:
    out = []
    for attr in ("args", "kwargs", "class_node", "parent"):
        v = getattr(node, attr, None)
        if isinstance(v, DAGNode):
            out.append(v)
        elif isinstance(v, (list, tuple)):
            out.extend(x for x in v if isinstance(x, DAGNode))
        elif isinstance(v, dict):
            out.extend(x for x in v.values() if isinstance(x, DAGNode))
    return out


def compile_jax_pipeline(stages, donate: bool = False):
    """Fuse a chain of pure-jax stage functions into one jitted program.

    The TPU-native compiled-DAG fast path: stage boundaries become in-program
    values (XLA schedules/overlaps them; on a sharded mesh the edges lower to
    ICI transfers), instead of host round-trips through the object store.
    """
    import jax

    def fused(x):
        for stage in stages:
            x = stage(x)
        return x

    return jax.jit(fused, donate_argnums=(0,) if donate else ())
