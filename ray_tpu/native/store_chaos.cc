// Chaos driver for the shm-arena object store, built under TSAN/ASAN
// (parity: the reference's sanitizer CI configs, .bazelrc asan/tsan).
//
// Usage: store_chaos <arena_path> <threads> <iters>
//
// The main thread initializes the arena; worker threads then each open their
// own Store handle over the same mapping (exactly what concurrent worker
// processes do) and hammer create/seal/get/verify/release/delete, including
// deliberate id collisions so the exists/tombstone and deferred-delete paths
// race. Exit code 0 + empty sanitizer report = pass.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <pthread.h>
#include <unistd.h>
#include <vector>

#include "rt_store.h"

namespace {

constexpr uint32_t kIdSize = 28;

struct WorkerArgs {
  const char* path;
  int tid;
  int iters;
  int shared_ids;  // collision space size across threads
};

void make_id(uint8_t* id, uint64_t key) {
  memset(id, 0, kIdSize);
  memcpy(id, &key, sizeof(key));
  id[kIdSize - 1] = 0x7f;  // non-zero tail so ids never look "empty"
}

void* worker(void* argp) {
  WorkerArgs* a = static_cast<WorkerArgs*>(argp);
  void* h = rt_store_open(a->path, 0, 0, 0);
  if (!h) {
    fprintf(stderr, "worker %d: open failed\n", a->tid);
    return (void*)1;
  }
  uint64_t rng = 0x9e3779b97f4a7c15ULL * (a->tid + 1);
  uint64_t failures = 0;
  for (int i = 0; i < a->iters; i++) {
    rng = rng * 6364136223846793005ULL + 1442695040888963407ULL;
    // half the keys are shared across threads to force collisions
    uint64_t key = (rng & 1) ? (rng >> 1) % a->shared_ids
                             : ((uint64_t)a->tid << 32) | i;
    uint8_t id[kIdSize];
    make_id(id, key);
    uint64_t size = 64 + (rng % 4096);
    int err = 0;
    uint64_t off = rt_store_create(h, id, size, &err);
    bool sealed_by_me = false;
    if (off) {
      uint8_t* base = static_cast<uint8_t*>(rt_store_base(h));
      memset(base + off, (int)(key & 0xff), size);
      if (rt_store_seal(h, id) != 0) failures++;
      sealed_by_me = true;
    } else if (err == 0) {
      failures++;  // create failed with no error code
    }
    uint64_t got_size = 0;
    uint64_t got = rt_store_get(h, id, &got_size);
    if (got) {
      uint8_t* base = static_cast<uint8_t*>(rt_store_base(h));
      // verify first/last byte under the pin, then release
      if (base[got] != (uint8_t)(key & 0xff) ||
          base[got + got_size - 1] != (uint8_t)(key & 0xff)) {
        // a collision-winner from another thread wrote a different key with
        // the same id only if keys differ — same id => same key => same fill,
        // so any mismatch is a real torn read
        failures++;
      }
      rt_store_release(h, id);
    }
    // delete only objects known sealed: the store forbids (and we must not
    // attempt) freeing a block another thread is still filling
    if ((rng >> 8) % 3 == 0 && (sealed_by_me || rt_store_contains(h, id)))
      rt_store_delete(h, id);
    if ((rng >> 16) % 64 == 0) {
      uint8_t vid[kIdSize];
      if (rt_store_lru_victim(h, vid)) rt_store_delete(h, vid);
    }
  }
  rt_store_close(h);
  return (void*)(uintptr_t)failures;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s <arena_path> <threads> <iters>\n", argv[0]);
    return 2;
  }
  const char* path = argv[1];
  int nthreads = atoi(argv[2]);
  int iters = atoi(argv[3]);
  unlink(path);
  void* h = rt_store_open(path, 64ull << 20, 8192, 1);
  if (!h) {
    fprintf(stderr, "init open failed\n");
    return 2;
  }
  std::vector<pthread_t> tids(nthreads);
  std::vector<WorkerArgs> args(nthreads);
  for (int t = 0; t < nthreads; t++) {
    args[t] = WorkerArgs{path, t, iters, 97};
    pthread_create(&tids[t], nullptr, worker, &args[t]);
  }
  uint64_t failures = 0;
  for (int t = 0; t < nthreads; t++) {
    void* ret = nullptr;
    pthread_join(tids[t], &ret);
    failures += (uintptr_t)ret;
  }
  rt_store_close(h);
  unlink(path);
  if (failures) {
    fprintf(stderr, "chaos failures: %llu\n", (unsigned long long)failures);
    return 1;
  }
  printf("ok\n");
  return 0;
}
