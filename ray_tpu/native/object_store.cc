// Shared-memory arena object store (plasma equivalent).
//
// Design parity: the reference's plasma store (src/ray/object_manager/plasma/,
// store.h:55) — mmap arena + allocator, sealed-object semantics, pinned reads,
// deferred free. Differences by design: instead of a store *server* process
// with a unix-socket protocol and fd-passing (plasma.fbs, fling), the arena
// itself is the shared medium: one mmap'd file in /dev/shm whose header holds
// a process-shared robust mutex and an open-addressing object table. Every
// client (driver or worker) maps the same file; create/seal/get are O(1)
// table operations under the lock; reads are zero-copy slices of the mapping.
//
// Layout:  [Header | Entry[table_size] | data region]
// Allocation: first-fit over a block list threaded through the data region
// (block headers precede payloads), with coalescing on free.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// Layout version tag: v2 added Header::prefault_cursor, which moved the
// shared pthread mutex — a v1 build locking a v2 arena (or vice versa)
// would "lock" the wrong bytes and race the allocator, so mixed builds
// must refuse to share an arena instead of silently corrupting it.
constexpr uint64_t kMagic = 0x5241595F54505632ULL;  // "RAY_TPV2"
constexpr uint32_t kIdSize = 28;

enum EntryState : uint32_t {
  kEmpty = 0,
  kCreating = 1,
  kSealed = 2,
  kTombstone = 3,
};

struct Entry {
  uint8_t id[kIdSize];
  uint32_t state;
  uint64_t offset;  // payload offset from arena base
  uint64_t size;    // payload size
  uint32_t pins;    // active reader pins
  uint32_t pending_delete;
  int32_t owner_pid;  // creator while kCreating (orphan reclaim)
  uint32_t pad_;
  uint64_t last_access;  // LRU clock value at last seal/get
};

// free/used block header threaded through the data region
struct Block {
  uint64_t size;      // payload capacity of this block
  uint64_t next_off;  // next free block offset (0 = none); valid when free
  uint32_t free_;
  uint32_t pad_;
};

struct Header {
  uint64_t magic;
  uint64_t capacity;       // total file size
  uint64_t data_off;       // start of data region
  uint64_t table_size;     // number of Entry slots
  uint64_t free_head;      // offset of first free block (0 = none)
  uint64_t used_bytes;     // payload bytes in sealed/creating objects
  uint64_t num_objects;
  uint64_t access_clock;   // monotonically increasing LRU clock
  uint64_t prefault_cursor;  // data-region high-water mark of prefaulted pages
  pthread_mutex_t mutex;
};

struct Store {
  uint8_t* base;
  Header* hdr;
  Entry* table;
  uint64_t mapped_size;
};

inline uint64_t align8(uint64_t v) { return (v + 7) & ~7ULL; }

inline uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 28-byte id
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

class LockGuard {
 public:
  explicit LockGuard(pthread_mutex_t* m) : m_(m) {
    int rc = pthread_mutex_lock(m_);
    if (rc == EOWNERDEAD) {
      // a client died holding the lock; state is still consistent enough for
      // our operations (all mutations are a few stores) — make it usable
      pthread_mutex_consistent(m_);
    }
  }
  ~LockGuard() { pthread_mutex_unlock(m_); }

 private:
  pthread_mutex_t* m_;
};

Entry* find_slot(Store* s, const uint8_t* id, bool for_insert) {
  const uint64_t n = s->hdr->table_size;
  uint64_t idx = hash_id(id) % n;
  Entry* first_tomb = nullptr;
  for (uint64_t probe = 0; probe < n; probe++) {
    Entry* e = &s->table[(idx + probe) % n];
    if (e->state == kEmpty) {
      if (for_insert) return first_tomb ? first_tomb : e;
      return nullptr;
    }
    if (e->state == kTombstone) {
      if (for_insert && !first_tomb) first_tomb = e;
      continue;
    }
    if (memcmp(e->id, id, kIdSize) == 0) return e;
  }
  return for_insert ? first_tomb : nullptr;
}

Block* block_at(Store* s, uint64_t off) {
  return reinterpret_cast<Block*>(s->base + off);
}

// allocate a payload of `size`; returns payload offset or 0
uint64_t alloc_block(Store* s, uint64_t size) {
  size = align8(size ? size : 8);
  uint64_t prev_off = 0;
  uint64_t off = s->hdr->free_head;
  while (off) {
    Block* b = block_at(s, off);
    if (b->size >= size) {
      uint64_t remain = b->size - size;
      if (remain > sizeof(Block) + 64) {
        // split: tail becomes a new free block
        uint64_t tail_off = off + sizeof(Block) + size;
        Block* tail = block_at(s, tail_off);
        tail->size = remain - sizeof(Block);
        tail->free_ = 1;
        tail->next_off = b->next_off;
        b->size = size;
        if (prev_off) {
          block_at(s, prev_off)->next_off = tail_off;
        } else {
          s->hdr->free_head = tail_off;
        }
      } else {
        if (prev_off) {
          block_at(s, prev_off)->next_off = b->next_off;
        } else {
          s->hdr->free_head = b->next_off;
        }
      }
      b->free_ = 0;
      b->next_off = 0;
      return off + sizeof(Block);
    }
    prev_off = off;
    off = b->next_off;
  }
  return 0;
}

void free_block(Store* s, uint64_t payload_off) {
  uint64_t off = payload_off - sizeof(Block);
  Block* b = block_at(s, off);
  b->free_ = 1;
  // address-ordered insert with coalescing of physically-adjacent neighbors
  uint64_t prev = 0;
  uint64_t cur = s->hdr->free_head;
  while (cur && cur < off) {
    prev = cur;
    cur = block_at(s, cur)->next_off;
  }
  // merge with next?
  if (cur && off + sizeof(Block) + b->size == cur) {
    Block* nb = block_at(s, cur);
    b->size += sizeof(Block) + nb->size;
    b->next_off = nb->next_off;
  } else {
    b->next_off = cur;
  }
  // merge with prev?
  if (prev) {
    Block* pb = block_at(s, prev);
    if (prev + sizeof(Block) + pb->size == off) {
      pb->size += sizeof(Block) + b->size;
      pb->next_off = b->next_off;
      return;
    }
    pb->next_off = off;
  } else {
    s->hdr->free_head = off;
  }
}

bool pid_alive(int32_t pid) {
  if (pid <= 0) return false;
  return kill(pid, 0) == 0 || errno == EPERM;
}

void do_delete(Store* s, Entry* e) {
  free_block(s, e->offset);
  s->hdr->used_bytes -= e->size;
  s->hdr->num_objects -= 1;
  e->state = kTombstone;
}

}  // namespace

extern "C" {

// returns an opaque handle (heap pointer) or null
void* rt_store_open(const char* path, uint64_t capacity, uint64_t table_size,
                    int create) {
  int fd = open(path, create ? (O_RDWR | O_CREAT) : O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t header_bytes = align8(sizeof(Header));
  uint64_t table_bytes = align8(sizeof(Entry) * table_size);
  bool init = false;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  if (st.st_size == 0) {
    if (!create) {
      close(fd);
      return nullptr;
    }
    if (ftruncate(fd, capacity) != 0) {
      close(fd);
      return nullptr;
    }
    init = true;
  } else {
    capacity = st.st_size;
  }
  void* mem =
      mmap(nullptr, capacity, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  // allocation-time buffer prep: huge pages shrink TLB pressure on the
  // multi-MiB copies this mapping exists for; WILLNEED primes already-
  // allocated pages. Both are advice — unsupported kernels just say no.
#ifdef MADV_WILLNEED
  madvise(mem, capacity, MADV_WILLNEED);
#endif
  Store* s = new Store();
  s->base = static_cast<uint8_t*>(mem);
  s->hdr = reinterpret_cast<Header*>(s->base);
  s->mapped_size = capacity;
  if (init) {
    memset(s->base, 0, header_bytes + table_bytes);
    s->hdr->capacity = capacity;
    s->hdr->data_off = header_bytes + table_bytes;
    s->hdr->table_size = table_size;
    s->hdr->used_bytes = 0;
    s->hdr->num_objects = 0;
    s->hdr->prefault_cursor = s->hdr->data_off;
    // one big free block spanning the data region
    uint64_t first = s->hdr->data_off;
    Block* b = reinterpret_cast<Block*>(s->base + first);
    b->size = capacity - first - sizeof(Block);
    b->free_ = 1;
    b->next_off = 0;
    s->hdr->free_head = first;
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&s->hdr->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
    __atomic_store_n(&s->hdr->magic, kMagic, __ATOMIC_RELEASE);
  } else {
    // wait for the creator to finish initializing; a foreign NONZERO magic
    // is a different layout version (or not our file) — fail fast instead
    // of spinning out the whole init window
    for (int i = 0; i < 100000; i++) {
      uint64_t m = __atomic_load_n(&s->hdr->magic, __ATOMIC_ACQUIRE);
      if (m == kMagic || m != 0) break;
      usleep(100);
    }
    if (s->hdr->magic != kMagic) {
      munmap(mem, capacity);
      delete s;
      return nullptr;
    }
  }
  s->table = reinterpret_cast<Entry*>(s->base + header_bytes);
  return s;
}

void rt_store_close(void* handle) {
  Store* s = static_cast<Store*>(handle);
  if (!s) return;
  munmap(s->base, s->mapped_size);
  delete s;
}

// create an object; returns payload offset (>0) or 0 on failure.
// rc semantics via errno-style out param: 1 = exists, 2 = full
uint64_t rt_store_create(void* handle, const uint8_t* id, uint64_t size,
                         int* err) {
  Store* s = static_cast<Store*>(handle);
  LockGuard g(&s->hdr->mutex);
  Entry* existing = find_slot(s, id, false);
  if (existing && existing->state == kCreating &&
      !pid_alive(existing->owner_pid)) {
    // creator died between create and seal: reclaim the orphan so retries of
    // the same deterministic object id can proceed (plasma does this via
    // per-client disconnect cleanup)
    do_delete(s, existing);
    existing = nullptr;
  }
  if (existing && existing->state != kTombstone) {
    *err = 1;
    return 0;
  }
  uint64_t off = alloc_block(s, size);
  if (!off) {
    *err = 2;
    return 0;
  }
  Entry* e = find_slot(s, id, true);
  if (!e) {  // table full
    free_block(s, off);
    *err = 2;
    return 0;
  }
  memcpy(e->id, id, kIdSize);
  e->state = kCreating;
  e->offset = off;
  e->size = size;
  e->pins = 0;
  e->pending_delete = 0;
  e->owner_pid = static_cast<int32_t>(getpid());
  s->hdr->used_bytes += size;
  s->hdr->num_objects += 1;
  *err = 0;
  return off;
}

int rt_store_seal(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  LockGuard g(&s->hdr->mutex);
  Entry* e = find_slot(s, id, false);
  if (!e || e->state != kCreating) return -1;
  e->state = kSealed;
  e->last_access = ++s->hdr->access_clock;
  return 0;
}

// get+pin: returns payload offset or 0 if not sealed/absent; fills size
uint64_t rt_store_get(void* handle, const uint8_t* id, uint64_t* size) {
  Store* s = static_cast<Store*>(handle);
  LockGuard g(&s->hdr->mutex);
  Entry* e = find_slot(s, id, false);
  if (!e || e->state != kSealed) return 0;
  e->pins += 1;
  e->last_access = ++s->hdr->access_clock;
  *size = e->size;
  return e->offset;
}

int rt_store_contains(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  LockGuard g(&s->hdr->mutex);
  Entry* e = find_slot(s, id, false);
  return (e && e->state == kSealed) ? 1 : 0;
}

// unpin a previously gotten object; performs deferred delete at pin==0
int rt_store_release(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  LockGuard g(&s->hdr->mutex);
  Entry* e = find_slot(s, id, false);
  if (!e || (e->state != kSealed && e->state != kCreating)) return -1;
  if (e->pins > 0) e->pins -= 1;
  if (e->pins == 0 && e->pending_delete) do_delete(s, e);
  return 0;
}

// creator-only abort of an unsealed object (plasma Abort): the one legal way
// to free a kCreating block, because only the creator knows no fill is in
// flight
int rt_store_abort(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  LockGuard g(&s->hdr->mutex);
  Entry* e = find_slot(s, id, false);
  if (!e || e->state != kCreating) return -1;
  if (e->owner_pid != static_cast<int32_t>(getpid())) return -1;
  do_delete(s, e);
  return 0;
}

int rt_store_delete(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  LockGuard g(&s->hdr->mutex);
  Entry* e = find_slot(s, id, false);
  if (!e || e->state == kTombstone || e->state == kEmpty) return -1;
  if (e->state == kCreating && pid_alive(e->owner_pid)) {
    // an unsealed object is deletable only once its creator has died (the
    // orphan-reclaim path); freeing the block while the creator is alive
    // would race its in-progress payload write
    return -1;
  }
  if (e->pins > 0) {
    e->pending_delete = 1;  // deferred until readers release
    return 0;
  }
  do_delete(s, e);
  return 0;
}

uint64_t rt_store_used_bytes(void* handle) {
  Store* s = static_cast<Store*>(handle);
  LockGuard g(&s->hdr->mutex);
  return s->hdr->used_bytes;
}

uint64_t rt_store_num_objects(void* handle) {
  Store* s = static_cast<Store*>(handle);
  LockGuard g(&s->hdr->mutex);
  return s->hdr->num_objects;
}

// base address of the mapping in THIS process (for python-side slicing)
void* rt_store_base(void* handle) {
  return static_cast<Store*>(handle)->base;
}

uint64_t rt_store_capacity(void* handle) {
  return static_cast<Store*>(handle)->hdr->capacity;
}

// Prefault up to max_bytes of not-yet-touched FREE arena space so later
// large-object copies write into resident pages instead of serializing
// first-touch faults inside the copy loop. Only free-block payloads are
// written (zeroed) — always safe under the lock — and a shared high-water
// cursor in the header makes the walk incremental and once-per-arena:
// pages below the cursor were either prefaulted here or touched by a real
// object write, and tmpfs pages stay resident for the file's lifetime once
// allocated. Returns bytes touched; 0 = nothing left to do. Callers hold
// the budget loop (one slab per call keeps lock holds bounded).
uint64_t rt_store_prefault(void* handle, uint64_t max_bytes) {
  Store* s = static_cast<Store*>(handle);
  LockGuard g(&s->hdr->mutex);
  uint64_t cursor = s->hdr->prefault_cursor;
  if (cursor < s->hdr->data_off) cursor = s->hdr->data_off;  // older arena
  uint64_t touched = 0;
  uint64_t off = s->hdr->free_head;
  while (off && touched < max_bytes) {
    Block* b = block_at(s, off);
    uint64_t lo = off + sizeof(Block);
    uint64_t hi = lo + b->size;
    if (hi > cursor) {
      uint64_t from = lo > cursor ? lo : cursor;
      uint64_t n = hi - from;
      if (n > max_bytes - touched) n = max_bytes - touched;
      memset(s->base + from, 0, n);
      touched += n;
      if (from + n > cursor) cursor = from + n;
    }
    off = b->next_off;
  }
  s->hdr->prefault_cursor = cursor;
  return touched;
}

// LRU eviction candidate (parity: plasma EvictionPolicy choosing sealed,
// unpinned objects; eviction_policy.h): fills out_id and returns 1, or
// returns 0 when nothing is evictable. The caller spills the object's bytes
// to secondary storage and then deletes it.
int rt_store_lru_victim(void* handle, uint8_t* out_id) {
  Store* s = static_cast<Store*>(handle);
  LockGuard g(&s->hdr->mutex);
  Entry* victim = nullptr;
  for (uint64_t i = 0; i < s->hdr->table_size; i++) {
    Entry* c = &s->table[i];
    if (c->state == kSealed && c->pins == 0 && !c->pending_delete) {
      if (!victim || c->last_access < victim->last_access) victim = c;
    }
  }
  if (!victim) return 0;
  memcpy(out_id, victim->id, kIdSize);
  return 1;
}

}  // extern "C"
