"""Native (C++) components, loaded via ctypes.

Parity: the reference's C++ core (SURVEY.md §2.1). Built with ``make`` in this
directory; pure-Python fallbacks exist for every component so the framework
degrades gracefully on hosts without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_LIB = None
_LIB_TRIED = False

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libray_tpu_native.so")


def _try_build() -> bool:
    try:
        subprocess.run(
            ["make", "-s"], cwd=_DIR, check=True, capture_output=True, timeout=120
        )
        return os.path.exists(_SO)
    except Exception:
        return False


def load_native():
    """Returns the loaded CDLL or None (builds on first use if needed)."""
    global _LIB, _LIB_TRIED
    if _LIB is not None or _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    # Rebuild (atomically, via make temp+rename) only when the source is
    # newer than the .so — a plain mtime compare keeps worker startup free
    # of subprocess overhead. A stale .so is never silently preferred.
    src = os.path.join(_DIR, "object_store.cc")
    try:
        stale = not os.path.exists(_SO) or (
            os.path.getmtime(src) > os.path.getmtime(_SO)
        )
    except OSError:
        stale = True
    if stale and not _try_build() and not os.path.exists(_SO):
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    lib.rt_store_open.restype = ctypes.c_void_p
    lib.rt_store_open.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
        ctypes.c_int,
    ]
    lib.rt_store_close.argtypes = [ctypes.c_void_p]
    lib.rt_store_create.restype = ctypes.c_uint64
    lib.rt_store_create.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int),
    ]
    lib.rt_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_store_get.restype = ctypes.c_uint64
    lib.rt_store_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.rt_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_store_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_store_used_bytes.restype = ctypes.c_uint64
    lib.rt_store_used_bytes.argtypes = [ctypes.c_void_p]
    lib.rt_store_num_objects.restype = ctypes.c_uint64
    lib.rt_store_num_objects.argtypes = [ctypes.c_void_p]
    lib.rt_store_base.restype = ctypes.c_void_p
    lib.rt_store_base.argtypes = [ctypes.c_void_p]
    lib.rt_store_capacity.restype = ctypes.c_uint64
    lib.rt_store_capacity.argtypes = [ctypes.c_void_p]
    lib.rt_store_lru_victim.restype = ctypes.c_int
    lib.rt_store_lru_victim.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8)]
    if hasattr(lib, "rt_store_prefault"):
        lib.rt_store_prefault.restype = ctypes.c_uint64
        lib.rt_store_prefault.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    _LIB = lib
    return _LIB
