// C ABI of the shm-arena object store (object_store.cc). Shared by the
// ctypes loader docs, the chaos driver, and any future native client so a
// signature change is a compile error, not a silent ABI mismatch.
#pragma once

#include <cstdint>

extern "C" {
void* rt_store_open(const char* path, uint64_t capacity, uint64_t table_size,
                    int create);
void rt_store_close(void* handle);
uint64_t rt_store_create(void* handle, const uint8_t* id, uint64_t size,
                         int* err);
int rt_store_seal(void* handle, const uint8_t* id);
uint64_t rt_store_get(void* handle, const uint8_t* id, uint64_t* size);
int rt_store_contains(void* handle, const uint8_t* id);
int rt_store_release(void* handle, const uint8_t* id);
int rt_store_abort(void* handle, const uint8_t* id);
int rt_store_delete(void* handle, const uint8_t* id);
uint64_t rt_store_used_bytes(void* handle);
uint64_t rt_store_num_objects(void* handle);
void* rt_store_base(void* handle);
uint64_t rt_store_capacity(void* handle);
int rt_store_lru_victim(void* handle, uint8_t* out_id);
uint64_t rt_store_prefault(void* handle, uint64_t max_bytes);
}
