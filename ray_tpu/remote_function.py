"""@remote functions.

Design parity: ``python/ray/remote_function.py:266`` (``RemoteFunction._remote``)
and option handling (``python/ray/_private/ray_option_utils.py``). The function
is cloudpickled once and cached (the reference exports once to the GCS function
table via ``_private/function_manager.py``).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.runtime_env import upload_runtime_env as _upload_runtime_env
from ray_tpu.util.tracing import for_submission as _trace_for_submission
from ray_tpu._private.task_spec import Arg, SchedulingStrategy, TaskSpec, TaskType
from ray_tpu._private.worker import ObjectRef, ObjectRefGenerator, get_runtime, pack_args

_DEFAULT_TASK_OPTIONS = dict(
    num_cpus=1.0,
    num_tpus=0.0,
    resources=None,
    num_returns=1,
    max_retries=3,
    retry_exceptions=False,
    scheduling_strategy=None,
    runtime_env=None,
    name=None,
    memory=None,
)


def resolve_resources(opts: Dict[str, Any]) -> Dict[str, float]:
    res = {k: float(v) for k, v in (opts.get("resources") or {}).items()}
    if opts.get("num_cpus"):
        res["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus"):  # accepted for API compat; maps onto the TPU pool
        res.setdefault("TPU", float(opts["num_gpus"]))
    if opts.get("memory"):
        res["memory"] = float(opts["memory"])
    return res


def _normalize_retry_exceptions(value):
    """False | True | exception class | list of classes -> False|True|names.

    Classes are stored as qualified-name strings: TaskSpec travels to workers
    over plain pickle (user classes may not import there), and pickling
    ``__main__`` classes by value breaks ``isinstance`` identity. The
    scheduler matches names against the raised cause's MRO.
    """
    if not value:
        return False
    if value is True:
        return True
    if isinstance(value, type) and issubclass(value, BaseException):
        value = [value]
    names = []
    for v in value:
        if not (isinstance(v, type) and issubclass(v, BaseException)):
            raise TypeError(
                f"retry_exceptions entries must be exception types, got {v!r}"
            )
        names.append(f"{v.__module__}.{v.__qualname__}")
    return names


def resolve_strategy(opts) -> SchedulingStrategy:
    strat = opts.get("scheduling_strategy")
    if strat is None:
        return SchedulingStrategy()
    if isinstance(strat, str):
        return SchedulingStrategy(kind=strat)
    return strat.to_internal()


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._function = fn
        self._name = getattr(fn, "__name__", "fn")
        self._options = dict(_DEFAULT_TASK_OPTIONS)
        self._options.update(options or {})
        self._pickled: Optional[bytes] = None
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._name}' cannot be called directly; use "
            f"'{self._name}.remote()' or '.bind()' in a DAG."
        )

    def options(self, **updates) -> "RemoteFunction":
        new = RemoteFunction(self._function, {**self._options, **updates})
        new._pickled = self._pickled
        return new

    def _get_pickled(self) -> bytes:
        if self._pickled is None:
            self._pickled = cloudpickle.dumps(self._function)
        return self._pickled

    def remote(self, *args, **kwargs):
        rt = get_runtime()
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        packed_args, packed_kwargs = pack_args(rt, args, kwargs)
        task_id = rt.new_task_id()
        spec = TaskSpec(
            task_id=task_id,
            task_type=TaskType.NORMAL_TASK,
            function=self._get_pickled(),
            args=packed_args,
            kwargs=packed_kwargs,
            num_returns=1 if streaming else int(num_returns),
            resources=resolve_resources(opts),
            name=opts.get("name") or self._name,
            max_retries=int(opts.get("max_retries") or 0),
            retry_exceptions=_normalize_retry_exceptions(
                opts.get("retry_exceptions")
            ),
            scheduling_strategy=resolve_strategy(opts),
            runtime_env=_upload_runtime_env(rt, opts.get("runtime_env")),
            is_streaming=streaming,
            trace_ctx=_trace_for_submission(),
        )
        rt.submit(spec)
        if streaming:
            return ObjectRefGenerator(spec.task_id, ObjectRef(ObjectID.for_return(spec.task_id, 0), _owned=True))
        refs = [ObjectRef(oid, _owned=True) for oid in spec.return_ids()]
        if spec.num_returns == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)
