"""Mixture-of-Experts MLP with expert parallelism.

Absent from the reference (SURVEY.md §2.3: expert parallel row — "absent");
first-class here: GShard-style top-k gating with capacity, dispatch/combine
einsums whose expert dimension shards over the ``expert`` mesh axis — GSPMD
lowers the dispatch to all-to-alls over ICI.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    d_model: int = 128
    d_ff: int = 512
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Tuple = jnp.float32


def init_moe_params(key, cfg: MoEConfig) -> Dict[str, jax.Array]:
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = 1.0 / jnp.sqrt(cfg.d_model)
    scale_out = 1.0 / jnp.sqrt(cfg.d_ff)
    return {
        "router": (jax.random.normal(k1, (cfg.d_model, cfg.num_experts)) * scale_in).astype(cfg.dtype),
        "w_in": (jax.random.normal(k2, (cfg.num_experts, cfg.d_model, cfg.d_ff)) * scale_in).astype(cfg.dtype),
        "w_out": (jax.random.normal(k3, (cfg.num_experts, cfg.d_ff, cfg.d_model)) * scale_out).astype(cfg.dtype),
    }


def moe_param_logical_axes() -> Dict[str, Tuple]:
    return {
        "router": ("embed", None),
        "w_in": ("expert", "embed", "mlp"),
        "w_out": ("expert", "mlp", "embed"),
    }


def moe_mlp(params: Dict[str, jax.Array], x: jax.Array, cfg: MoEConfig):
    """x: (B, S, D) -> (y (B, S, D), aux_loss).

    GShard dispatch: tokens are routed to their top-k experts with a per-
    expert capacity; overflow tokens are dropped (their residual passes
    through). aux_loss is the standard load-balancing loss.
    """
    B, S, D = x.shape
    T = B * S
    E, K = cfg.num_experts, cfg.top_k
    xt = x.reshape(T, D)

    logits = (xt.astype(jnp.float32) @ params["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # (T, E)

    # top-k selection
    gate_vals, expert_idx = jax.lax.top_k(probs, K)  # (T, K)
    gate_vals = gate_vals / jnp.maximum(gate_vals.sum(-1, keepdims=True), 1e-9)

    capacity = max(1, int(cfg.capacity_factor * T * K / E))

    # position of each (token, k) within its expert's capacity
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)  # (T, K, E)
    flat = onehot.reshape(T * K, E)
    pos_in_expert = jnp.cumsum(flat, axis=0) * flat - 1  # (T*K, E)
    pos = pos_in_expert.reshape(T, K, E).max(-1)  # (T, K) position, -1 if none
    within = (pos >= 0) & (pos < capacity)

    # dispatch tensor (T, E, C) and combine weights
    dispatch = jnp.zeros((T, E, capacity), jnp.float32)
    combine = jnp.zeros((T, E, capacity), jnp.float32)
    t_idx = jnp.arange(T)[:, None].repeat(K, 1)
    safe_pos = jnp.clip(pos, 0, capacity - 1)
    dispatch = dispatch.at[t_idx, expert_idx, safe_pos].add(within.astype(jnp.float32))
    combine = combine.at[t_idx, expert_idx, safe_pos].add(
        (gate_vals * within).astype(jnp.float32)
    )

    # expert compute: (E, C, D) — expert dim shards over the 'expert' axis
    expert_in = jnp.einsum("tec,td->ecd", dispatch, xt.astype(jnp.float32))
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"].astype(jnp.float32)))
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"].astype(jnp.float32))
    yt = jnp.einsum("tec,ecd->td", combine, expert_out)

    # load-balancing loss (Shazeer et al.): E * sum_e f_e * p_e
    token_frac = jnp.mean(
        jax.nn.one_hot(expert_idx[:, 0], E, dtype=jnp.float32), axis=0
    )
    prob_frac = jnp.mean(probs, axis=0)
    aux_loss = E * jnp.sum(token_frac * prob_frac)

    return yt.reshape(B, S, D).astype(x.dtype), aux_loss
