"""Vision Transformer (ViT), TPU-first.

Widens the model-family coverage beyond the LM/MoE/MLP/CNN families (the
reference frameworks' train/serve stacks are model-agnostic; vision models are
their second-most-common workload). Same design rules as
``models/transformer.py``:

* patch embedding is a reshape + one matmul (pure MXU work — no conv needed
  for non-overlapping patches);
* stacked per-layer params scanned with ``jax.lax.scan`` — one compiled block
  body regardless of depth;
* every parameter carries logical axes (``param_logical_axes``) so DP/FSDP/TP
  are annotation changes through ``ray_tpu.parallel.sharding``;
* bfloat16 compute with fp32 norms; bidirectional (non-causal) attention.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.transformer import _init
from ray_tpu.ops.attention import attention
from ray_tpu.ops.layers import gelu, rms_norm


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    num_classes: int = 1000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16
    remat: bool = False

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.num_channels

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


# CI-sized and standard presets
VIT_TINY_TEST = ViTConfig(image_size=32, patch_size=8, num_classes=10,
                          d_model=64, n_layers=2, n_heads=4, d_ff=128)
VIT_B_16 = ViTConfig()  # ViT-Base/16 geometry (public standard)
VIT_L_16 = ViTConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096)


def init_params(key: jax.Array, cfg: ViTConfig) -> Dict[str, jax.Array]:
    keys = jax.random.split(key, 10)
    L, D, H, Hd, F = cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff
    dt = cfg.dtype
    s_in = 1.0 / math.sqrt(D)
    return {
        "patch_embed": _init(keys[0], (cfg.patch_dim, D), 1.0 / math.sqrt(cfg.patch_dim), dt),
        "pos_embed": _init(keys[1], (cfg.num_patches + 1, D), 0.02, jnp.float32),
        "cls_token": _init(keys[2], (D,), 0.02, jnp.float32),
        "wq": _init(keys[3], (L, D, H, Hd), s_in, dt),
        "wk": _init(keys[4], (L, D, H, Hd), s_in, dt),
        "wv": _init(keys[5], (L, D, H, Hd), s_in, dt),
        "wo": _init(keys[6], (L, H, Hd, D), s_in / math.sqrt(2 * L), dt),
        "attn_norm": jnp.ones((L, D), jnp.float32),
        "mlp_norm": jnp.ones((L, D), jnp.float32),
        "w_up": _init(keys[7], (L, D, F), s_in, dt),
        "w_down": _init(keys[8], (L, F, D), 1.0 / math.sqrt(F) / math.sqrt(2 * L), dt),
        "final_norm": jnp.ones((D,), jnp.float32),
        "head": _init(keys[9], (D, cfg.num_classes), s_in, dt),
    }


def param_logical_axes(cfg: ViTConfig) -> Dict[str, Tuple]:
    return {
        "patch_embed": ("patch", "embed"),
        "pos_embed": (None, "embed"),
        "cls_token": ("embed",),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "heads", "head_dim"),
        "wv": ("layers", "embed", "heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "attn_norm": ("layers", "norm"),
        "mlp_norm": ("layers", "norm"),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
        "final_norm": ("norm",),
        "head": ("embed", "vocab"),
    }


def patchify(cfg: ViTConfig, images: jax.Array) -> jax.Array:
    """(B, H, W, C) -> (B, num_patches, patch_dim) by pure reshape/transpose
    (non-overlapping patches need no convolution)."""
    B = images.shape[0]
    P = cfg.patch_size
    n = cfg.image_size // P
    x = images.reshape(B, n, P, n, P, cfg.num_channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, n, n, P, P, C)
    return x.reshape(B, n * n, cfg.patch_dim)


def _block(cfg: ViTConfig, x: jax.Array, layer: Dict) -> jax.Array:
    h = rms_norm(x, layer["attn_norm"])
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"])
    att = attention(q, k, v, causal=False)
    x = x + jnp.einsum("bshk,hkd->bsd", att, layer["wo"])
    m = rms_norm(x, layer["mlp_norm"])
    ff = gelu(jnp.einsum("bsd,df->bsf", m, layer["w_up"]))
    return x + jnp.einsum("bsf,fd->bsd", ff, layer["w_down"])


def forward(cfg: ViTConfig, params: Dict, images: jax.Array) -> jax.Array:
    """images (B, H, W, C) float -> logits (B, num_classes)."""
    x = patchify(cfg, images).astype(cfg.dtype) @ params["patch_embed"]
    B = x.shape[0]
    cls = jnp.broadcast_to(params["cls_token"].astype(cfg.dtype), (B, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(cfg.dtype)[None]

    stacked = {
        k: params[k]
        for k in ("wq", "wk", "wv", "wo", "attn_norm", "mlp_norm", "w_up", "w_down")
    }

    def body(carry, layer):
        fn = _block
        if cfg.remat:
            fn = jax.checkpoint(_block, static_argnums=(0,))
        return fn(cfg, carry, layer), None

    x, _ = jax.lax.scan(body, x, stacked)
    x = rms_norm(x, params["final_norm"])
    # classify on the CLS token in fp32
    return (x[:, 0, :] @ params["head"]).astype(jnp.float32)


def loss_fn(cfg: ViTConfig, params: Dict, images: jax.Array, labels: jax.Array):
    logits = forward(cfg, params, images)
    logp = jax.nn.log_softmax(logits)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
    acc = jnp.mean((jnp.argmax(logits, axis=1) == labels).astype(jnp.float32))
    return loss, acc
