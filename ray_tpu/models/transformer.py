"""Decoder-only transformer LM, TPU-first.

Design choices for the MXU/XLA (SURVEY.md §7, BASELINE.md north-star GPT-J):

* params are a flat dict of stacked per-layer arrays scanned with
  ``jax.lax.scan`` — one compiled block body regardless of depth;
* every parameter has a logical-axes tuple (``param_logical_axes``) consumed
  by ``ray_tpu.parallel.sharding`` so DP/FSDP/TP/CP are pure annotation
  changes;
* bfloat16 activations/weights with fp32 norm/softmax accumulation;
* GPT-J-style *parallel* attention+MLP block (``parallel_block=True``) or
  Llama-style sequential block; RoPE positions are explicit so context
  parallelism can feed absolute positions per shard;
* attention dispatches to the Pallas flash kernel on TPU, or ring attention
  when a ``context`` axis is active (``context_axis`` argument).

Config presets cover the benchmark models named in BASELINE.json: GPT-J-6B
(fine-tune target) and Llama-2-7B (serve target), plus tiny variants for CI.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import attention, ring_attention
from ray_tpu.ops.layers import apply_rope, gelu, rms_norm, rope_frequencies, swiglu


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: Optional[int] = None  # None = MHA
    d_ff: int = 11008
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    parallel_block: bool = False  # True = GPT-J style
    use_swiglu: bool = True  # False = gelu MLP (GPT-J)
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    remat: bool = True  # jax.checkpoint each block (HBM <-> FLOPs trade)
    # None = full recompute; "dots" saves matmul outputs so the backward pass
    # re-runs only cheap elementwise work (~6N total FLOPs instead of ~8N) at
    # the cost of keeping per-layer projection outputs in HBM
    remat_policy: Optional[str] = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    def num_params(self) -> int:
        p = self.vocab_size * self.d_model  # embed
        if not self.tie_embeddings:
            p += self.vocab_size * self.d_model
        per_layer = (
            self.d_model * self.n_heads * self.head_dim  # wq
            + 2 * self.d_model * self.kv_heads * self.head_dim  # wk, wv
            + self.n_heads * self.head_dim * self.d_model  # wo
            + (3 if self.use_swiglu else 2) * self.d_model * self.d_ff
            + 2 * self.d_model  # norms
        )
        return p + self.n_layers * per_layer + self.d_model


# -- presets (shapes match the public model cards; cited for parity with
# BASELINE.json configs, not copied code) -----------------------------------

GPTJ_6B = TransformerConfig(
    vocab_size=50400,
    d_model=4096,
    n_layers=28,
    n_heads=16,
    d_ff=16384,
    max_seq_len=2048,
    parallel_block=True,
    use_swiglu=False,
    tie_embeddings=False,
)

LLAMA2_7B = TransformerConfig(
    vocab_size=32000,
    d_model=4096,
    n_layers=32,
    n_heads=32,
    d_ff=11008,
    max_seq_len=4096,
)

TINY = TransformerConfig(
    vocab_size=256,
    d_model=128,
    n_layers=2,
    n_heads=4,
    d_ff=512,
    max_seq_len=128,
    remat=False,
)


def _init(key, shape, scale, dtype):
    return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)


def init_params(key: jax.Array, cfg: TransformerConfig) -> Dict[str, jax.Array]:
    """Stacked-layer parameter dict."""
    keys = jax.random.split(key, 10)
    L, D, H, KV, Hd, F = (
        cfg.n_layers,
        cfg.d_model,
        cfg.n_heads,
        cfg.kv_heads,
        cfg.head_dim,
        cfg.d_ff,
    )
    dt = cfg.dtype
    s_in = 1.0 / math.sqrt(D)
    s_ff = 1.0 / math.sqrt(F)
    params = {
        "embed": _init(keys[0], (cfg.vocab_size, D), 0.02, dt),
        "wq": _init(keys[1], (L, D, H, Hd), s_in, dt),
        "wk": _init(keys[2], (L, D, KV, Hd), s_in, dt),
        "wv": _init(keys[3], (L, D, KV, Hd), s_in, dt),
        "wo": _init(keys[4], (L, H, Hd, D), s_in / math.sqrt(2 * L), dt),
        "attn_norm": jnp.ones((L, D), jnp.float32),
        "mlp_norm": jnp.ones((L, D), jnp.float32),
        "w_up": _init(keys[5], (L, D, F), s_in, dt),
        "w_down": _init(keys[6], (L, F, D), s_ff / math.sqrt(2 * L), dt),
        "final_norm": jnp.ones((D,), jnp.float32),
    }
    if cfg.use_swiglu:
        params["w_gate"] = _init(keys[7], (L, D, F), s_in, dt)
    if not cfg.tie_embeddings:
        params["unembed"] = _init(keys[8], (D, cfg.vocab_size), s_in, dt)
    return params


def param_logical_axes(cfg: TransformerConfig) -> Dict[str, Tuple]:
    """Logical sharding axes per parameter (see parallel/sharding.py rules)."""
    axes = {
        "embed": ("vocab", "embed"),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "attn_norm": ("layers", "norm"),
        "mlp_norm": ("layers", "norm"),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
        "final_norm": ("norm",),
    }
    if cfg.use_swiglu:
        axes["w_gate"] = ("layers", "embed", "mlp")
    if not cfg.tie_embeddings:
        axes["unembed"] = ("embed", "vocab")
    return axes


def _block(cfg: TransformerConfig, x, layer, cos, sin, positions, context_axis, mesh):
    """One transformer block. x: (B, S, D)."""
    h = rms_norm(x, layer["attn_norm"])
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"])
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"])
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"])
    q = apply_rope(q, cos, sin, positions)
    k = apply_rope(k, cos, sin, positions)
    if context_axis is not None:
        # partial-manual shard_map: only the context axis goes manual (ring
        # ppermute over ICI); batch/model axes stay under GSPMD
        import functools

        from jax.sharding import PartitionSpec as P

        from ray_tpu.parallel._shard_map import shard_map as _shard_map

        spec = P(None, context_axis, None, None)
        att = _shard_map(
            functools.partial(ring_attention, axis_name=context_axis, causal=True),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            axis_names={context_axis},
        )(q, k, v)
    else:
        att = attention(q, k, v, causal=True)
    att_out = jnp.einsum("bshk,hkd->bsd", att, layer["wo"])

    if cfg.parallel_block:
        # GPT-J: MLP reads the same normed input; both branches add to residual
        m = h
    else:
        x = x + att_out
        m = rms_norm(x, layer["mlp_norm"])
    if cfg.use_swiglu:
        ff = swiglu(
            jnp.einsum("bsd,df->bsf", m, layer["w_gate"]),
            jnp.einsum("bsd,df->bsf", m, layer["w_up"]),
        )
    else:
        ff = gelu(jnp.einsum("bsd,df->bsf", m, layer["w_up"]))
    mlp_out = jnp.einsum("bsf,fd->bsd", ff, layer["w_down"])
    if cfg.parallel_block:
        return x + att_out + mlp_out
    return x + mlp_out


def forward(
    params: Dict[str, jax.Array],
    tokens: jax.Array,
    cfg: TransformerConfig,
    *,
    positions: Optional[jax.Array] = None,
    context_axis: Optional[str] = None,
    mesh=None,
) -> jax.Array:
    """tokens (B, S) -> logits (B, S, vocab). With ``context_axis`` (+``mesh``)
    attention runs as a ring over that axis; ``positions`` must then be the
    absolute token positions of this shard's slice of the sequence."""
    x = params["embed"][tokens]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)

    stacked = {
        k: v for k, v in params.items() if k not in ("embed", "unembed", "final_norm")
    }

    def body(x, layer):
        out = _block(cfg, x, layer, cos, sin, positions, context_axis, mesh)
        return out, None

    if cfg.remat:
        if cfg.remat_policy == "dots":
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            )
        else:
            body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, stacked)
    x = rms_norm(x, params["final_norm"])
    unembed = params.get("unembed")
    if unembed is None:
        unembed = params["embed"].T
    return jnp.einsum("bsd,dv->bsv", x, unembed)


def loss_fn(
    params,
    tokens: jax.Array,
    targets: jax.Array,
    cfg: TransformerConfig,
    *,
    positions=None,
    context_axis=None,
    mesh=None,
    loss_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Mean next-token cross-entropy in fp32."""
    logits = forward(
        params, tokens, cfg, positions=positions, context_axis=context_axis, mesh=mesh
    ).astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if loss_mask is not None:
        return jnp.sum(nll * loss_mask) / jnp.maximum(jnp.sum(loss_mask), 1.0)
    return jnp.mean(nll)
