"""Model zoo: decoder-only LMs (GPT-J/Llama families), MNIST nets, MoE.

These play the role of the reference's example/benchmark workloads
(``release/train_tests``, ``rllib/tuned_examples``) but are first-class here:
every model declares logical sharding axes so it runs under any mesh.
"""

from ray_tpu.models.transformer import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    param_logical_axes,
)

__all__ = [
    "TransformerConfig",
    "init_params",
    "forward",
    "loss_fn",
    "param_logical_axes",
]

from ray_tpu.models import vit  # noqa: E402  (ViT family: models/vit.py)

__all__.append("vit")
