"""MNIST-scale models (MLP + small CNN) for the DP benchmark config.

Parity target: BASELINE.json config #2 "Ray Train MNIST -> JaxTrainer
(4-chip DP)". Pure-jax params/apply so the same code runs the 8-device CPU
test mesh and real chips.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def init_mlp(key, hidden: Tuple[int, ...] = (128, 128), num_classes: int = 10,
             input_dim: int = 784) -> Dict:
    sizes = (input_dim,) + tuple(hidden) + (num_classes,)
    keys = jax.random.split(key, len(sizes) - 1)
    return {
        "layers": [
            {
                "w": jax.random.normal(k, (sizes[i], sizes[i + 1]))
                * jnp.sqrt(2.0 / sizes[i]),
                "b": jnp.zeros(sizes[i + 1]),
            }
            for i, k in enumerate(keys)
        ]
    }


def apply_mlp(params: Dict, x: jax.Array) -> jax.Array:
    h = x.reshape(x.shape[0], -1)
    for i, layer in enumerate(params["layers"]):
        h = h @ layer["w"] + layer["b"]
        if i < len(params["layers"]) - 1:
            h = jax.nn.relu(h)
    return h


def init_cnn(key, num_classes: int = 10) -> Dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {
        "conv1": jax.random.normal(k1, (3, 3, 1, 16)) * 0.1,
        "conv2": jax.random.normal(k2, (3, 3, 16, 32)) * 0.1,
        "fc1": {
            "w": jax.random.normal(k3, (7 * 7 * 32, 128)) * 0.02,
            "b": jnp.zeros(128),
        },
        "fc2": {
            "w": jax.random.normal(k4, (128, num_classes)) * 0.02,
            "b": jnp.zeros(num_classes),
        },
    }


def apply_cnn(params: Dict, x: jax.Array) -> jax.Array:
    """x: (B, 28, 28, 1) -> logits (B, 10)."""
    h = jax.lax.conv_general_dilated(
        x, params["conv1"], (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )
    h = jax.nn.relu(h)
    h = jax.lax.reduce_window(h, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
    h = jax.lax.conv_general_dilated(
        h, params["conv2"], (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")
    )
    h = jax.nn.relu(h)
    h = jax.lax.reduce_window(h, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")
    h = h.reshape(h.shape[0], -1)
    h = jax.nn.relu(h @ params["fc1"]["w"] + params["fc1"]["b"])
    return h @ params["fc2"]["w"] + params["fc2"]["b"]


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=1)[:, 0]
    return jnp.mean(logz - gold)


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
