"""Autoregressive decoding with a KV cache.

The inference half of the model family: prefill + single-token decode steps
over a static-shape cache, jit-compiled once (cache donated between steps so
decode is in-place on device). The reference serves LLMs by delegating to
external engines on top of Serve; here the decode path is in-tree and
TPU-native: static shapes for XLA, masked attention over the cache instead
of data-dependent slicing, bf16 weights with fp32 logits.

Layout: cache k/v are (L, B, max_len, kv_heads, head_dim).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.ops.layers import apply_rope, gelu, rms_norm, rope_frequencies, swiglu

_NEG_INF = -1e30


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int) -> Dict:
    shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "pos": jnp.zeros((), jnp.int32),
    }


def _stacked(params):
    return {
        k: v
        for k, v in params.items()
        if k not in ("embed", "unembed", "final_norm")
    }


def _mlp(cfg, layer, m):
    if cfg.use_swiglu:
        ff = swiglu(
            jnp.einsum("bsd,df->bsf", m, layer["w_gate"]),
            jnp.einsum("bsd,df->bsf", m, layer["w_up"]),
        )
    else:
        ff = gelu(jnp.einsum("bsd,df->bsf", m, layer["w_up"]))
    return jnp.einsum("bsf,fd->bsd", ff, layer["w_down"])


def _cached_attention(q, ck, cv, cache_positions, q_positions):
    """q (B,S,H,Hd) against the full cache (B,M,KV,Hd), masked to entries at
    cache_positions <= q_positions (causal over absolute positions) and
    cache_positions < written length."""
    n_rep = q.shape[2] // ck.shape[2]
    if n_rep > 1:
        b, m, kv, d = ck.shape
        ck = jnp.broadcast_to(ck[:, :, :, None, :], (b, m, kv, n_rep, d)).reshape(
            b, m, kv * n_rep, d
        )
        cv = jnp.broadcast_to(cv[:, :, :, None, :], (b, m, kv, n_rep, d)).reshape(
            b, m, kv * n_rep, d
        )
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, ck, preferred_element_type=jnp.float32)
    scores = scores * scale
    mask = cache_positions[None, :] <= q_positions[:, None]  # (S, M)
    scores = jnp.where(mask[None, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, cv)


def _forward_cached(params, tokens, positions, cache, cfg: TransformerConfig):
    """Run the model over ``tokens`` (B,S) at absolute ``positions`` (S,),
    reading+writing the KV cache. Returns (logits (B,S,V), cache)."""
    x = params["embed"][tokens]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    max_len = cache["k"].shape[2]
    cache_positions = jnp.arange(max_len)
    start = cache["pos"]

    def body(carry, layer_inputs):
        x = carry
        layer, ck, cv = layer_inputs
        h = rms_norm(x, layer["attn_norm"])
        q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"])
        k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"])
        v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"])
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        # write this step's k/v into the cache at [start, start+S)
        ck = jax.lax.dynamic_update_slice(ck, k, (0, start, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v, (0, start, 0, 0))
        att = _cached_attention(q, ck, cv, cache_positions, positions)
        att_out = jnp.einsum("bshk,hkd->bsd", att, layer["wo"])
        if cfg.parallel_block:
            m = h
            x_out = x + att_out + _mlp(cfg, layer, m)
        else:
            x1 = x + att_out
            m = rms_norm(x1, layer["mlp_norm"])
            x_out = x1 + _mlp(cfg, layer, m)
        return x_out, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (_stacked(params), cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"])
    unembed = params.get("unembed")
    if unembed is None:
        unembed = params["embed"].T
    logits = jnp.einsum("bsd,dv->bsv", x, unembed).astype(jnp.float32)
    new_cache = {"k": new_k, "v": new_v, "pos": start + tokens.shape[1]}
    return logits, new_cache


def make_decode_fns(cfg: TransformerConfig, max_len: int):
    """Returns (prefill, decode_step), both jitted with donated caches.

    prefill(params, tokens, cache) -> (last_logits (B,V), cache)
    decode_step(params, token (B,1), cache) -> (logits (B,V), cache)
    """

    @functools.partial(jax.jit, donate_argnums=(2,))
    def prefill(params, tokens, cache):
        positions = jnp.arange(tokens.shape[1])
        logits, cache = _forward_cached(params, tokens, positions, cache, cfg)
        return logits[:, -1, :], cache

    @functools.partial(jax.jit, donate_argnums=(2,))
    def decode_step(params, token, cache):
        positions = cache["pos"][None]
        logits, cache = _forward_cached(params, token, positions, cache, cfg)
        return logits[:, -1, :], cache

    return prefill, decode_step


def generate(
    params,
    prompt_tokens,
    cfg: TransformerConfig,
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
    fns: Optional[Tuple] = None,
) -> jnp.ndarray:
    """Greedy (temperature 0) or sampled decoding; returns (B, new) tokens."""
    import numpy as np

    prompt_tokens = jnp.asarray(prompt_tokens)
    if prompt_tokens.ndim == 1:
        prompt_tokens = prompt_tokens[None, :]
    b, s = prompt_tokens.shape
    max_len = s + max_new_tokens
    if max_len > cfg.max_seq_len:
        # the rope tables are sized to max_seq_len; jit's clamped gathers
        # would silently reuse the last position's rotary embedding
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({cfg.max_seq_len})"
        )
    prefill, decode_step = fns or make_decode_fns(cfg, max_len)
    cache = init_kv_cache(cfg, b, max_len)
    logits, cache = prefill(params, prompt_tokens, cache)
    out = []
    if key is None:
        key = jax.random.PRNGKey(0)
    for i in range(max_new_tokens):
        if temperature and temperature > 0:
            key, sub = jax.random.split(key)
            tok = jax.random.categorical(sub, logits / temperature)
        else:
            tok = jnp.argmax(logits, axis=-1)
        out.append(tok)
        if i + 1 < max_new_tokens:  # the last token needs no further logits
            logits, cache = decode_step(params, tok[:, None], cache)
    return jnp.stack(out, axis=1)
