"""Autoregressive decoding with a KV cache.

The inference half of the model family: prefill + single-token decode steps
over a static-shape cache, jit-compiled once (cache donated between steps so
decode is in-place on device). The reference serves LLMs by delegating to
external engines on top of Serve; here the decode path is in-tree and
TPU-native: static shapes for XLA, masked attention over the cache instead
of data-dependent slicing, bf16 weights with fp32 logits.

Layout: cache k/v are (L, B, max_len, kv_heads, head_dim).

Two cache layouts share the same attention math:

* dense (``init_kv_cache`` + ``make_decode_fns``): per-batch contiguous
  cache, all sequences advance in lockstep — the static-batch demo path.
* paged (``init_paged_pool`` + ``make_paged_fns``): one device-wide pool of
  fixed-size blocks; each sequence owns a block table mapping absolute
  positions to pool slots. Shapes stay static (block tables are dense
  int32 arrays padded with the reserved null block 0), so the serve
  plane's continuous-batching engine reuses one compiled decode step no
  matter which sequences occupy the batch slots.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.ops.layers import apply_rope, gelu, rms_norm, rope_frequencies, swiglu

_NEG_INF = -1e30


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int) -> Dict:
    shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
        "pos": jnp.zeros((), jnp.int32),
    }


def _stacked(params):
    return {
        k: v
        for k, v in params.items()
        if k not in ("embed", "unembed", "final_norm")
    }


def _mlp(cfg, layer, m):
    if cfg.use_swiglu:
        ff = swiglu(
            jnp.einsum("bsd,df->bsf", m, layer["w_gate"]),
            jnp.einsum("bsd,df->bsf", m, layer["w_up"]),
        )
    else:
        ff = gelu(jnp.einsum("bsd,df->bsf", m, layer["w_up"]))
    return jnp.einsum("bsf,fd->bsd", ff, layer["w_down"])


def _cached_attention(q, ck, cv, cache_positions, q_positions):
    """q (B,S,H,Hd) against the full cache (B,M,KV,Hd), masked to entries at
    cache_positions <= q_positions (causal over absolute positions) and
    cache_positions < written length."""
    n_rep = q.shape[2] // ck.shape[2]
    if n_rep > 1:
        b, m, kv, d = ck.shape
        ck = jnp.broadcast_to(ck[:, :, :, None, :], (b, m, kv, n_rep, d)).reshape(
            b, m, kv * n_rep, d
        )
        cv = jnp.broadcast_to(cv[:, :, :, None, :], (b, m, kv, n_rep, d)).reshape(
            b, m, kv * n_rep, d
        )
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, ck, preferred_element_type=jnp.float32)
    scores = scores * scale
    mask = cache_positions[None, :] <= q_positions[:, None]  # (S, M)
    scores = jnp.where(mask[None, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, cv)


def _forward_cached(params, tokens, positions, cache, cfg: TransformerConfig):
    """Run the model over ``tokens`` (B,S) at absolute ``positions`` (S,),
    reading+writing the KV cache. Returns (logits (B,S,V), cache)."""
    x = params["embed"][tokens]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)
    max_len = cache["k"].shape[2]
    cache_positions = jnp.arange(max_len)
    start = cache["pos"]

    def body(carry, layer_inputs):
        x = carry
        layer, ck, cv = layer_inputs
        h = rms_norm(x, layer["attn_norm"])
        q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"])
        k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"])
        v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"])
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        # write this step's k/v into the cache at [start, start+S)
        ck = jax.lax.dynamic_update_slice(ck, k, (0, start, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, v, (0, start, 0, 0))
        att = _cached_attention(q, ck, cv, cache_positions, positions)
        att_out = jnp.einsum("bshk,hkd->bsd", att, layer["wo"])
        if cfg.parallel_block:
            m = h
            x_out = x + att_out + _mlp(cfg, layer, m)
        else:
            x1 = x + att_out
            m = rms_norm(x1, layer["mlp_norm"])
            x_out = x1 + _mlp(cfg, layer, m)
        return x_out, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (_stacked(params), cache["k"], cache["v"])
    )
    x = rms_norm(x, params["final_norm"])
    unembed = params.get("unembed")
    if unembed is None:
        unembed = params["embed"].T
    logits = jnp.einsum("bsd,dv->bsv", x, unembed).astype(jnp.float32)
    new_cache = {"k": new_k, "v": new_v, "pos": start + tokens.shape[1]}
    return logits, new_cache


def make_decode_fns(cfg: TransformerConfig, max_len: int):
    """Returns (prefill, decode_step), both jitted with donated caches.

    prefill(params, tokens, cache) -> (last_logits (B,V), cache)
    decode_step(params, token (B,1), cache) -> (logits (B,V), cache)
    """

    @functools.partial(jax.jit, donate_argnums=(2,))
    def prefill(params, tokens, cache):
        positions = jnp.arange(tokens.shape[1])
        logits, cache = _forward_cached(params, tokens, positions, cache, cfg)
        return logits[:, -1, :], cache

    @functools.partial(jax.jit, donate_argnums=(2,))
    def decode_step(params, token, cache):
        positions = cache["pos"][None]
        logits, cache = _forward_cached(params, token, positions, cache, cfg)
        return logits[:, -1, :], cache

    return prefill, decode_step


# -- paged KV cache ----------------------------------------------------------
#
# The pool is (L, num_blocks * block_size, kv_heads, head_dim): flat slot
# addressing, where block b covers slots [b*block_size, (b+1)*block_size).
# Block 0 is reserved as the null block: padded block-table entries and
# masked-out writes land there, and its (garbage) contents are always
# behind the causal mask, so attention never reads them.


def _kv_storage_dtype(dtype):
    """Storage dtype for the paged pool: 16-bit floats are stored as their
    raw bits (uint16). XLA's CPU backend expands sub-32-bit float scatters
    into a whole-pool f32 convert/convert-back pair — an O(pool-size)
    memcpy per layer per step — while integer scatters stay native and
    in-place. Bitcasting the few written/gathered rows at the edges is
    free and bitwise-identical to storing the float directly."""
    d = jnp.dtype(dtype)
    return jnp.uint16 if d.itemsize == 2 else d


def init_paged_pool(
    cfg: TransformerConfig, num_blocks: int, block_size: int
) -> Dict:
    """Preallocated device pool for the paged KV cache (block 0 reserved).

    Entries are ``cfg.dtype`` values; 16-bit dtypes are held as raw bits
    (see ``_kv_storage_dtype``) and bitcast at the scatter/gather edges."""
    n_slots = num_blocks * block_size
    shape = (cfg.n_layers, n_slots, cfg.kv_heads, cfg.head_dim)
    st = _kv_storage_dtype(cfg.dtype)
    return {"k": jnp.zeros(shape, st), "v": jnp.zeros(shape, st)}


def _paged_attention(q, gk, gv, q_positions):
    """q (B,S,H,Hd) against gathered block rows (B,M,KV,Hd) whose row index
    IS the absolute position (block p of a table covers positions
    [p*bs, (p+1)*bs)); causal mask row <= q_position per batch element.
    Same scale/mask/softmax forms as ``_cached_attention`` so dense and
    paged decode agree tokenwise."""
    n_rep = q.shape[2] // gk.shape[2]
    if n_rep > 1:
        b, m, kv, d = gk.shape
        gk = jnp.broadcast_to(gk[:, :, :, None, :], (b, m, kv, n_rep, d)).reshape(
            b, m, kv * n_rep, d
        )
        gv = jnp.broadcast_to(gv[:, :, :, None, :], (b, m, kv, n_rep, d)).reshape(
            b, m, kv * n_rep, d
        )
    scale = 1.0 / (q.shape[-1] ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, gk, preferred_element_type=jnp.float32)
    scores = scores * scale
    m = gk.shape[1]
    mask = jnp.arange(m)[None, None, :] <= q_positions[:, :, None]  # (B,S,M)
    scores = jnp.where(mask[:, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, gv)


def _forward_paged(
    params,
    tokens,
    positions,
    write_mask,
    block_tables,
    pool,
    cfg: TransformerConfig,
    block_size: int,
):
    """Run the model over ``tokens`` (B,S) at per-sequence absolute
    ``positions`` (B,S), scattering k/v into the block pool and attending
    over each sequence's gathered blocks. ``write_mask`` (B,S) diverts
    padded rows to the null block; ``block_tables`` (B, max_blocks) maps
    block index -> pool block (0-padded). Returns (logits (B,S,V), pool)."""
    b, s = tokens.shape
    mb = block_tables.shape[1]
    x = params["embed"][tokens]
    cos, sin = rope_frequencies(cfg.head_dim, cfg.max_seq_len, cfg.rope_theta)

    # flat slot destination per (b, s) token; masked rows -> null block 0
    pidx = jnp.clip(positions // block_size, 0, mb - 1)
    slot = (
        jnp.take_along_axis(block_tables, pidx, axis=1) * block_size
        + positions % block_size
    )
    null_slot = jnp.arange(b * s, dtype=slot.dtype) % block_size
    write_slots = jnp.where(write_mask.reshape(-1), slot.reshape(-1), null_slot)

    # gathered pool rows per sequence: row index == absolute position
    gather_idx = (
        block_tables[:, :, None] * block_size
        + jnp.arange(block_size)[None, None, :]
    ).reshape(b, mb * block_size)

    # The pool rides in the scan CARRY (updated at a dynamic layer index),
    # not in the per-layer ys: stacked scan outputs allocate a fresh slab
    # and copy every layer's full k/v through it, which defeats buffer
    # donation and turns each decode step into an O(pool-size) memcpy.
    # Carry-threaded updates alias in place under ``donate_argnums``.
    def body(carry, layer_inputs):
        x, pk, pv = carry
        layer, li = layer_inputs
        h = rms_norm(x, layer["attn_norm"])
        q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"])
        k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"])
        v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"])
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        # round to cfg.dtype, then scatter/gather in the pool's STORAGE
        # dtype (raw bits for 16-bit floats): float16-family scatters are
        # expanded by the CPU backend into whole-pool convert pairs, so
        # only the written/gathered rows may change representation here
        bits = pk.dtype != jnp.dtype(cfg.dtype)
        kw = k.reshape(b * s, *k.shape[2:]).astype(cfg.dtype)
        vw = v.reshape(b * s, *v.shape[2:]).astype(cfg.dtype)
        if bits:
            kw = jax.lax.bitcast_convert_type(kw, pk.dtype)
            vw = jax.lax.bitcast_convert_type(vw, pv.dtype)
        pk = pk.at[li, write_slots].set(kw)
        pv = pv.at[li, write_slots].set(vw)
        gk, gv = pk[li][gather_idx], pv[li][gather_idx]
        if bits:
            gk = jax.lax.bitcast_convert_type(gk, cfg.dtype)
            gv = jax.lax.bitcast_convert_type(gv, cfg.dtype)
        att = _paged_attention(q, gk, gv, positions)
        att_out = jnp.einsum("bshk,hkd->bsd", att, layer["wo"])
        if cfg.parallel_block:
            m = h
            x_out = x + att_out + _mlp(cfg, layer, m)
        else:
            x1 = x + att_out
            m = rms_norm(x1, layer["mlp_norm"])
            x_out = x1 + _mlp(cfg, layer, m)
        return (x_out, pk, pv), None

    (x, new_k, new_v), _ = jax.lax.scan(
        body,
        (x, pool["k"], pool["v"]),
        (_stacked(params), jnp.arange(cfg.n_layers)),
    )
    x = rms_norm(x, params["final_norm"])
    unembed = params.get("unembed")
    if unembed is None:
        unembed = params["embed"].T
    logits = jnp.einsum("bsd,dv->bsv", x, unembed).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def make_paged_fns(cfg: TransformerConfig, *, block_size: int):
    """Returns (prefill, decode_step, decode_step_greedy) over a paged
    pool, jitted with the pool donated (in-place on device between steps).

    prefill(params, tokens (1,S), block_table (1,MB), pool, length ())
        -> (logits at position length-1 (1,V), pool)
    decode_step(params, tokens (B,), positions (B,), block_tables (B,MB),
        pool, active (B,) bool) -> (logits (B,V), pool)
    decode_step_greedy(same args) -> (next tokens (B,) int32, pool)
        — argmax fused on device so a greedy batch ships B ints to the
        host per step instead of B x vocab logits (the hot serving path;
        identical tokens to argmax over ``decode_step``'s logits).

    Shapes are static per (S, MB, B): the engine buckets prompt lengths
    and runs decode at a fixed max batch, so each compiles exactly once.
    """

    @functools.partial(jax.jit, donate_argnums=(3,))
    def prefill(params, tokens, block_table, pool, length):
        s = tokens.shape[1]
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], tokens.shape)
        write_mask = positions < length
        logits, pool = _forward_paged(
            params, tokens, positions, write_mask, block_table, pool, cfg, block_size
        )
        last = jax.lax.dynamic_index_in_dim(logits, length - 1, axis=1, keepdims=False)
        return last, pool

    @functools.partial(jax.jit, donate_argnums=(4,))
    def decode_step(params, tokens, positions, block_tables, pool, active):
        logits, pool = _forward_paged(
            params,
            tokens[:, None],
            positions[:, None],
            active[:, None],
            block_tables,
            pool,
            cfg,
            block_size,
        )
        return logits[:, 0, :], pool

    @functools.partial(jax.jit, donate_argnums=(4,))
    def decode_step_greedy(params, tokens, positions, block_tables, pool, active):
        logits, pool = _forward_paged(
            params,
            tokens[:, None],
            positions[:, None],
            active[:, None],
            block_tables,
            pool,
            cfg,
            block_size,
        )
        return jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32), pool

    return prefill, decode_step, decode_step_greedy


# -- sampling ----------------------------------------------------------------


def sample_token(
    logits,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    key: Optional[jax.Array] = None,
):
    """Next-token selection from ``logits`` (..., V): greedy argmax when
    temperature <= 0 (the bitwise-stable default), else temperature
    scaling with optional top-k filtering before categorical sampling."""
    if not temperature or temperature <= 0:
        return jnp.argmax(logits, axis=-1)
    if key is None:
        raise ValueError("sampling (temperature > 0) requires a PRNG key")
    scaled = logits / temperature
    if top_k and top_k > 0:
        kth = jax.lax.top_k(scaled, int(top_k))[0][..., -1:]
        scaled = jnp.where(scaled < kth, _NEG_INF, scaled)
    return jax.random.categorical(key, scaled, axis=-1)


def sequence_key(seed: int, step: int) -> jax.Array:
    """Per-sequence PRNG stream, deterministic in (seed, step) and
    independent of batch composition — continuous batching samples the
    same tokens for a sequence no matter which neighbours share the
    decode step."""
    return jax.random.fold_in(jax.random.PRNGKey(int(seed)), int(step))


def generate(
    params,
    prompt_tokens,
    cfg: TransformerConfig,
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    key: Optional[jax.Array] = None,
    fns: Optional[Tuple] = None,
) -> jnp.ndarray:
    """Greedy (temperature 0) or sampled decoding; returns (B, new) tokens."""
    import numpy as np

    prompt_tokens = jnp.asarray(prompt_tokens)
    if prompt_tokens.ndim == 1:
        prompt_tokens = prompt_tokens[None, :]
    b, s = prompt_tokens.shape
    max_len = s + max_new_tokens
    if max_len > cfg.max_seq_len:
        # the rope tables are sized to max_seq_len; jit's clamped gathers
        # would silently reuse the last position's rotary embedding
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({cfg.max_seq_len})"
        )
    prefill, decode_step = fns or make_decode_fns(cfg, max_len)
    cache = init_kv_cache(cfg, b, max_len)
    logits, cache = prefill(params, prompt_tokens, cache)
    out = []
    if key is None:
        key = jax.random.PRNGKey(0)
    for i in range(max_new_tokens):
        if temperature and temperature > 0:
            key, sub = jax.random.split(key)
            tok = sample_token(
                logits, temperature=temperature, top_k=top_k, key=sub
            )
        else:
            tok = jnp.argmax(logits, axis=-1)
        out.append(tok)
        if i + 1 < max_new_tokens:  # the last token needs no further logits
            logits, cache = decode_step(params, tok[:, None], cache)
    return jnp.stack(out, axis=1)
