"""Job submission: run entrypoint commands as supervised cluster jobs.

Parity: ``python/ray/dashboard/modules/job`` — ``JobSubmissionClient`` /
``JobManager`` (``job_manager.py:57``): each job gets a detached
``JobSupervisor`` actor (``job_supervisor.py:51``) running the entrypoint as a
subprocess, status + logs recorded (here: GCS KV + log files in the session
dir).
"""

from __future__ import annotations

import enum
import json
import os
import time
import uuid
from typing import Dict, List, Optional

import ray_tpu

_NS = "jobs"


class JobStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


@ray_tpu.remote(max_concurrency=4)
class JobSupervisor:
    """Runs one entrypoint subprocess; parity: job_supervisor.py:51."""

    def __init__(self, job_id: str, entrypoint: str, log_path: str, env: Optional[dict]):
        import subprocess
        import threading

        self.job_id = job_id
        self.entrypoint = entrypoint
        self.log_path = log_path
        self.returncode: Optional[int] = None
        full_env = dict(os.environ)
        full_env.update(env or {})
        self._logf = open(log_path, "wb")
        self.proc = subprocess.Popen(
            entrypoint,
            shell=True,
            stdout=self._logf,
            stderr=subprocess.STDOUT,
            env=full_env,
        )
        self._waiter = threading.Thread(target=self._wait, daemon=True)
        self._waiter.start()

    def _wait(self):
        self.returncode = self.proc.wait()
        self._logf.flush()

    def status(self) -> str:
        if self.returncode is None:
            return JobStatus.RUNNING
        return JobStatus.SUCCEEDED if self.returncode == 0 else JobStatus.FAILED

    def stop(self) -> bool:
        if self.returncode is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except Exception:
                self.proc.kill()
        return True

    def logs(self) -> str:
        self._logf.flush()
        try:
            with open(self.log_path, "rb") as fh:
                return fh.read().decode(errors="replace")
        except FileNotFoundError:
            return ""


class JobSubmissionClient:
    """Parity: ``ray.job_submission.JobSubmissionClient`` (in-process mode)."""

    def __init__(self, address: Optional[str] = None):
        self._rt = ray_tpu.get_runtime()

    def _kv_put(self, job_id: str, record: dict):
        blob = json.dumps(record).encode()
        rt = ray_tpu.get_runtime()
        if hasattr(rt, "scheduler_rpc"):
            rt.scheduler_rpc("kv_put", (_NS, job_id.encode(), blob, True))
        else:
            rt.rpc("kv_put", _NS, job_id.encode(), blob, True)

    def _kv_get(self, job_id: str) -> Optional[dict]:
        rt = ray_tpu.get_runtime()
        if hasattr(rt, "scheduler_rpc"):
            raw = rt.scheduler_rpc("kv_get", (_NS, job_id.encode()))
        else:
            raw = rt.rpc("kv_get", _NS, job_id.encode())
        return json.loads(raw) if raw else None

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
    ) -> str:
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        driver = ray_tpu.get_runtime()
        log_dir = os.path.join(driver.node.session_dir, "logs") if hasattr(driver, "node") else "/tmp"
        log_path = os.path.join(log_dir, f"job-{job_id}.log")
        env = (runtime_env or {}).get("env_vars")
        supervisor = JobSupervisor.options(
            name=f"_job_supervisor:{job_id}", num_cpus=0
        ).remote(job_id, entrypoint, log_path, env)
        self._kv_put(
            job_id,
            {
                "job_id": job_id,
                "entrypoint": entrypoint,
                "submitted_at": time.time(),
                "metadata": metadata or {},
                "log_path": log_path,
            },
        )
        # surface immediate spawn failures
        ray_tpu.get(supervisor.status.remote(), timeout=60)
        return job_id

    def _supervisor(self, job_id: str):
        return ray_tpu.get_actor(f"_job_supervisor:{job_id}")

    def get_job_status(self, job_id: str) -> JobStatus:
        try:
            sup = self._supervisor(job_id)
        except ValueError:
            rec = self._kv_get(job_id)
            if rec is None:
                raise ValueError(f"unknown job {job_id}") from None
            return JobStatus.STOPPED
        return JobStatus(ray_tpu.get(sup.status.remote(), timeout=60))

    def get_job_logs(self, job_id: str) -> str:
        sup = self._supervisor(job_id)
        return ray_tpu.get(sup.logs.remote(), timeout=60)

    def stop_job(self, job_id: str) -> bool:
        sup = self._supervisor(job_id)
        return ray_tpu.get(sup.stop.remote(), timeout=60)

    def list_jobs(self) -> List[dict]:
        rt = ray_tpu.get_runtime()
        if hasattr(rt, "scheduler_rpc"):
            keys = rt.scheduler_rpc("kv_keys", (_NS, b""))
        else:
            keys = rt.rpc("kv_keys", _NS, b"")
        out = []
        for k in keys:
            rec = self._kv_get(k.decode())
            if rec:
                try:
                    rec["status"] = self.get_job_status(rec["job_id"]).value
                except Exception:
                    rec["status"] = "UNKNOWN"
                out.append(rec)
        return out

    def wait_until_finished(self, job_id: str, timeout: float = 600.0) -> JobStatus:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")
