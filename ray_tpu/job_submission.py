"""Job submission: run entrypoint commands as supervised cluster jobs.

Parity: ``python/ray/dashboard/modules/job`` — ``JobSubmissionClient`` /
``JobManager`` (``job_manager.py:57``): each job gets a detached
``JobSupervisor`` actor (``job_supervisor.py:51``) running the entrypoint as a
subprocess, status + logs recorded (here: GCS KV + log files in the session
dir).
"""

from __future__ import annotations

import enum
import json
import os
import time
import uuid
from typing import Dict, List, Optional

import ray_tpu

_NS = "jobs"


class JobStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


@ray_tpu.remote(max_concurrency=4)
class JobSupervisor:
    """Runs one entrypoint subprocess; parity: job_supervisor.py:51.

    Multi-tenant plane: when the submission registered an arbitration
    job (``arb_job`` hex), the supervisor holds the entrypoint until the
    scheduler ADMITS it — a QUEUED job's process never starts burning
    resources — and exports ``RAY_TPU_JOB_ID`` so the entrypoint's driver
    binds its tasks/puts to the job's quota, weight, and priority."""

    def __init__(
        self,
        job_id: str,
        entrypoint: str,
        log_path: str,
        env: Optional[dict],
        arb_job: Optional[str] = None,
    ):
        import threading

        self.job_id = job_id
        self.entrypoint = entrypoint
        self.log_path = log_path
        self.returncode: Optional[int] = None
        self.proc = None
        self._arb_job = arb_job
        self._stopped = False
        self._lock = threading.Lock()
        full_env = dict(os.environ)
        full_env.update(env or {})
        if arb_job:
            full_env["RAY_TPU_JOB_ID"] = arb_job
        self._logf = open(log_path, "wb")
        self._waiter = threading.Thread(
            target=self._run, args=(full_env,), daemon=True
        )
        self._waiter.start()

    def _admission(self) -> str:
        try:
            rt = ray_tpu.get_runtime()
            row = rt.rpc("job_info", self._arb_job)
            return (row or {}).get("admission", "ADMITTED")
        except Exception:
            return "ADMITTED"

    def _run(self, full_env):
        import subprocess

        while self._arb_job and not self._stopped:
            adm = self._admission()
            if adm == "ADMITTED":
                break
            if adm == "REJECTED":
                self._logf.write(b"job rejected by admission control\n")
                self._logf.flush()
                self.returncode = 126
                return
            time.sleep(0.25)
        # stopped-check and launch are one atomic step: a stop() landing
        # between them would otherwise return with proc still None and the
        # entrypoint would launch unsupervised right after
        with self._lock:
            if self._stopped:
                self.returncode = 143
                return
            self.proc = subprocess.Popen(
                self.entrypoint,
                shell=True,
                stdout=self._logf,
                stderr=subprocess.STDOUT,
                env=full_env,
            )
        self.returncode = self.proc.wait()
        self._logf.flush()

    def status(self) -> str:
        if self.proc is None and self.returncode is None:
            return JobStatus.PENDING  # waiting for admission
        if self.returncode is None:
            return JobStatus.RUNNING
        if self.returncode == 0:
            return JobStatus.SUCCEEDED
        return JobStatus.STOPPED if self._stopped else JobStatus.FAILED

    def stop(self) -> bool:
        with self._lock:
            self._stopped = True
        if self.returncode is None and self.proc is not None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except Exception:
                self.proc.kill()
        return True

    def logs(self) -> str:
        self._logf.flush()
        try:
            with open(self.log_path, "rb") as fh:
                return fh.read().decode(errors="replace")
        except FileNotFoundError:
            return ""


class JobSubmissionClient:
    """Parity: ``ray.job_submission.JobSubmissionClient`` (in-process mode)."""

    def __init__(self, address: Optional[str] = None):
        self._rt = ray_tpu.get_runtime()

    def _kv_put(self, job_id: str, record: dict):
        blob = json.dumps(record).encode()
        rt = ray_tpu.get_runtime()
        if hasattr(rt, "scheduler_rpc"):
            rt.scheduler_rpc("kv_put", (_NS, job_id.encode(), blob, True))
        else:
            rt.rpc("kv_put", _NS, job_id.encode(), blob, True)

    def _kv_get(self, job_id: str) -> Optional[dict]:
        rt = ray_tpu.get_runtime()
        if hasattr(rt, "scheduler_rpc"):
            raw = rt.scheduler_rpc("kv_get", (_NS, job_id.encode()))
        else:
            raw = rt.rpc("kv_get", _NS, job_id.encode())
        return json.loads(raw) if raw else None

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[dict] = None,
        priority: int = 0,
        weight: float = 1.0,
        quota: Optional[Dict[str, float]] = None,
    ) -> str:
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        driver = ray_tpu.get_runtime()
        # register the tenant with the scheduler's arbitration plane:
        # admission control decides ADMITTED / QUEUED / REJECTED here,
        # before any process is spawned
        rt = ray_tpu.get_runtime()
        arb_args = (
            job_id,
            int(priority),
            float(weight),
            quota,
            {"entrypoint": entrypoint, "submission_id": job_id},
        )
        if hasattr(rt, "scheduler_rpc"):
            arb = rt.scheduler_rpc("submit_job", arb_args)
        else:
            arb = rt.rpc("submit_job", *arb_args)
        if arb["admission"] == "REJECTED":
            from ray_tpu.exceptions import JobAdmissionError

            raise JobAdmissionError(
                f"job {job_id} rejected by admission control "
                f"(queue full or backlog bound exceeded)"
            )
        log_dir = os.path.join(driver.node.session_dir, "logs") if hasattr(driver, "node") else "/tmp"
        log_path = os.path.join(log_dir, f"job-{job_id}.log")
        env = (runtime_env or {}).get("env_vars")
        supervisor = JobSupervisor.options(
            name=f"_job_supervisor:{job_id}", num_cpus=0
        ).remote(job_id, entrypoint, log_path, env, arb["job"])
        self._kv_put(
            job_id,
            {
                "job_id": job_id,
                "entrypoint": entrypoint,
                "submitted_at": time.time(),
                "metadata": metadata or {},
                "log_path": log_path,
                "job": arb["job"],
                "priority": int(priority),
                "weight": float(weight),
                "quota": dict(quota or {}),
                "admission": arb["admission"],
            },
        )
        # surface immediate spawn failures
        ray_tpu.get(supervisor.status.remote(), timeout=60)
        return job_id

    def _supervisor(self, job_id: str):
        return ray_tpu.get_actor(f"_job_supervisor:{job_id}")

    def get_job_status(self, job_id: str) -> JobStatus:
        try:
            sup = self._supervisor(job_id)
        except ValueError:
            rec = self._kv_get(job_id)
            if rec is None:
                raise ValueError(f"unknown job {job_id}") from None
            return JobStatus.STOPPED
        return JobStatus(ray_tpu.get(sup.status.remote(), timeout=60))

    def get_job_logs(self, job_id: str) -> str:
        sup = self._supervisor(job_id)
        return ray_tpu.get(sup.logs.remote(), timeout=60)

    def stop_job(self, job_id: str) -> bool:
        sup = self._supervisor(job_id)
        return ray_tpu.get(sup.stop.remote(), timeout=60)

    def list_jobs(self) -> List[dict]:
        rt = ray_tpu.get_runtime()
        if hasattr(rt, "scheduler_rpc"):
            keys = rt.scheduler_rpc("kv_keys", (_NS, b""))
        else:
            keys = rt.rpc("kv_keys", _NS, b"")
        # join each submission record with its live arbitration row
        # (admission state, usage, queue position) by job hex
        from ray_tpu.util import state as _state

        try:
            arb_rows = {row["job"]: row for row in _state.list_jobs()}
        except Exception:
            arb_rows = {}
        out = []
        for k in keys:
            rec = self._kv_get(k.decode())
            if rec:
                try:
                    rec["status"] = self.get_job_status(rec["job_id"]).value
                except Exception:
                    rec["status"] = "UNKNOWN"
                arb = arb_rows.get(rec.get("job"))
                if arb:
                    for col in (
                        "admission",
                        "usage",
                        "object_store_bytes",
                        "running",
                        "ready",
                        "queue_position",
                        "preemptions",
                        "oom_kills",
                    ):
                        rec[col] = arb.get(col)
                out.append(rec)
        return out

    def wait_until_finished(self, job_id: str, timeout: float = 600.0) -> JobStatus:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job {job_id} still running after {timeout}s")
