"""Trial schedulers: early stopping policies.

Parity: ``python/ray/tune/schedulers/`` — FIFO (no-op), ASHA
(``async_hyperband.py``: successive-halving rungs, keep top 1/reduction_factor
per rung), median stopping rule (``median_stopping_rule.py``).
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, iteration: int, metrics: Dict) -> str:
        return CONTINUE


class ASHAScheduler:
    """Async successive halving.

    A trial reaching rung r (iteration == grace_period * reduction_factor**r)
    continues only if its metric is in the top 1/reduction_factor of completed
    results at that rung.
    """

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
    ):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        # rung level -> list of recorded metric values
        self._rungs: Dict[int, List[float]] = collections.defaultdict(list)

    def _rung_levels(self):
        level = self.grace
        while level < self.max_t:
            yield level
            level *= self.rf

    def on_result(self, trial_id: str, iteration: int, metrics: Dict) -> str:
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        if iteration >= self.max_t:
            return STOP
        for level in self._rung_levels():
            if iteration == level:
                rung = self._rungs[level]
                rung.append(float(value))
                if len(rung) < self.rf:
                    return CONTINUE  # not enough peers yet: optimistic continue
                srt = sorted(rung, reverse=(self.mode == "max"))
                cutoff = srt[max(0, len(rung) // self.rf - 1)]
                good = value >= cutoff if self.mode == "max" else value <= cutoff
                return CONTINUE if good else STOP
        return CONTINUE


class MedianStoppingRule:
    """Stop a trial whose running-average metric is worse than the median of
    other trials' running averages at the same iteration."""

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        grace_period: int = 1,
        min_samples_required: int = 3,
    ):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, List[float]] = collections.defaultdict(list)

    def on_result(self, trial_id: str, iteration: int, metrics: Dict) -> str:
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        self._history[trial_id].append(float(value))
        if iteration < self.grace:
            return CONTINUE
        averages = [
            sum(h) / len(h)
            for tid, h in self._history.items()
            if tid != trial_id and h
        ]
        if len(averages) < self.min_samples:
            return CONTINUE
        averages.sort()
        median = averages[len(averages) // 2]
        mine = sum(self._history[trial_id]) / len(self._history[trial_id])
        if self.mode == "min":
            return CONTINUE if mine <= median else STOP
        return CONTINUE if mine >= median else STOP


class HyperBandScheduler:
    """Bracketed successive halving (parity: ``tune/schedulers/hyperband.py``).

    Classic HyperBand runs ``s_max+1`` brackets that trade exploration
    breadth against per-trial budget: bracket ``s`` starts trials with
    grace period ``max_t / eta**s`` and halves by ``eta`` at each rung.
    Trials are assigned to brackets round-robin on first report. Rung
    decisions are made asynchronously per trial (no pausing — the async
    variant the reference recommends for elastic executors), so each
    bracket behaves like ASHA at its own grace period while the bracket
    spread preserves HyperBand's budget diversity."""

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        max_t: int = 81,
        reduction_factor: int = 3,
    ):
        assert mode in ("min", "max")
        assert reduction_factor > 1, "reduction_factor must be > 1"
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.rf = reduction_factor
        s_max = 0
        t = max_t
        while t > 1:
            t //= reduction_factor
            s_max += 1
        self._brackets = [
            ASHAScheduler(
                metric=metric,
                mode=mode,
                max_t=max_t,
                grace_period=max(1, max_t // (reduction_factor ** s)),
                reduction_factor=reduction_factor,
            )
            for s in range(s_max, -1, -1)
        ]
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def on_result(self, trial_id: str, iteration: int, metrics: Dict) -> str:
        b = self._assignment.get(trial_id)
        if b is None:
            b = self._assignment[trial_id] = self._next % len(self._brackets)
            self._next += 1
        return self._brackets[b].on_result(trial_id, iteration, metrics)


EXPLOIT = "EXPLOIT"


class PopulationBasedTraining:
    """PBT (parity: ``python/ray/tune/schedulers/pbt.py:1``): every
    ``perturbation_interval`` iterations a trial in the bottom quantile stops,
    clones a top-quantile trial's config + checkpoint, perturbs the mutated
    hyperparameters, and resumes. The Tuner performs the clone/relaunch when
    this scheduler returns EXPLOIT."""

    def __init__(
        self,
        *,
        metric: str,
        mode: str = "max",
        perturbation_interval: int = 4,
        hyperparam_mutations: Optional[Dict] = None,
        quantile_fraction: float = 0.25,
        seed: Optional[int] = None,
    ):
        import random as _random

        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.interval = int(perturbation_interval)
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self._rng = _random.Random(seed)
        # trial_id -> (iteration, score) at the last completed interval
        self._scores: Dict[str, tuple] = {}
        self._last_perturb: Dict[str, int] = {}

    def _norm(self, value: float) -> float:
        return value if self.mode == "max" else -value

    def on_result(self, trial_id: str, iteration: int, metrics: Dict) -> str:
        if self.metric not in metrics:
            return CONTINUE
        self._scores[trial_id] = (iteration, self._norm(float(metrics[self.metric])))
        if iteration - self._last_perturb.get(trial_id, 0) < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = iteration
        scores = [s for _, s in self._scores.values()]
        if len(scores) < 2:
            return CONTINUE
        scores_sorted = sorted(scores)
        k = max(1, int(len(scores_sorted) * self.quantile))
        bottom_cut = scores_sorted[k - 1]
        my = self._scores[trial_id][1]
        if my <= bottom_cut and my < scores_sorted[-1]:
            return EXPLOIT
        return CONTINUE

    def choose_exploit_source(self, trial_id: str, trials: Dict[str, dict]):
        """Pick a top-quantile trial to clone (not the exploiting one)."""
        ranked = sorted(
            (
                (self._scores[t][1], t)
                for t in trials
                if t in self._scores and t != trial_id
            ),
            reverse=True,
        )
        if not ranked:
            return None
        k = max(1, int(len(ranked) * self.quantile))
        return self._rng.choice([t for _, t in ranked[:k]])

    def mutate_config(self, config: Dict) -> Dict:
        out = dict(config)
        for key, spec in self.mutations.items():
            if callable(spec):
                out[key] = spec()
            elif isinstance(spec, list):
                # reference semantics: resample with prob 0.25, else keep the
                # exploited trial's (winning) value
                if self._rng.random() < 0.25 or key not in out:
                    out[key] = self._rng.choice(spec)
            elif key in out and isinstance(out[key], (int, float)):
                # numeric perturbation: *1.2 or *0.8 like the reference
                out[key] = out[key] * self._rng.choice([0.8, 1.2])
        return out
