"""Trial schedulers: early stopping policies.

Parity: ``python/ray/tune/schedulers/`` — FIFO (no-op), ASHA
(``async_hyperband.py``: successive-halving rungs, keep top 1/reduction_factor
per rung), median stopping rule (``median_stopping_rule.py``).
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, iteration: int, metrics: Dict) -> str:
        return CONTINUE


class ASHAScheduler:
    """Async successive halving.

    A trial reaching rung r (iteration == grace_period * reduction_factor**r)
    continues only if its metric is in the top 1/reduction_factor of completed
    results at that rung.
    """

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
    ):
        assert mode in ("min", "max")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        # rung level -> list of recorded metric values
        self._rungs: Dict[int, List[float]] = collections.defaultdict(list)

    def _rung_levels(self):
        level = self.grace
        while level < self.max_t:
            yield level
            level *= self.rf

    def on_result(self, trial_id: str, iteration: int, metrics: Dict) -> str:
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        if iteration >= self.max_t:
            return STOP
        for level in self._rung_levels():
            if iteration == level:
                rung = self._rungs[level]
                rung.append(float(value))
                if len(rung) < self.rf:
                    return CONTINUE  # not enough peers yet: optimistic continue
                srt = sorted(rung, reverse=(self.mode == "max"))
                cutoff = srt[max(0, len(rung) // self.rf - 1)]
                good = value >= cutoff if self.mode == "max" else value <= cutoff
                return CONTINUE if good else STOP
        return CONTINUE


class MedianStoppingRule:
    """Stop a trial whose running-average metric is worse than the median of
    other trials' running averages at the same iteration."""

    def __init__(
        self,
        metric: str = "loss",
        mode: str = "min",
        grace_period: int = 1,
        min_samples_required: int = 3,
    ):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._history: Dict[str, List[float]] = collections.defaultdict(list)

    def on_result(self, trial_id: str, iteration: int, metrics: Dict) -> str:
        value = metrics.get(self.metric)
        if value is None:
            return CONTINUE
        self._history[trial_id].append(float(value))
        if iteration < self.grace:
            return CONTINUE
        averages = [
            sum(h) / len(h)
            for tid, h in self._history.items()
            if tid != trial_id and h
        ]
        if len(averages) < self.min_samples:
            return CONTINUE
        averages.sort()
        median = averages[len(averages) // 2]
        mine = sum(self._history[trial_id]) / len(self._history[trial_id])
        if self.mode == "min":
            return CONTINUE if mine <= median else STOP
        return CONTINUE if mine >= median else STOP
