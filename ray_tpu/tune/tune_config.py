"""TuneConfig. Parity: ``python/ray/tune/tune_config.py``."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "min"  # "min" | "max"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    scheduler: Optional[Any] = None  # FIFOScheduler/ASHAScheduler/...
    search_alg: Optional[Any] = None
    seed: Optional[int] = None
