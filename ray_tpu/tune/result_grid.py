"""ResultGrid. Parity: ``python/ray/tune/result_grid.py``."""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.train._result import Result


class ResultGrid:
    def __init__(self, results: List[Result]):
        self._results = results

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self):
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(
        self, metric: Optional[str] = None, mode: str = "min"
    ) -> Result:
        candidates = [
            r for r in self._results if r.error is None and metric in r.metrics
        ]
        if not candidates:
            candidates = [r for r in self._results if r.error is None]
        if not candidates:
            raise RuntimeError("all trials failed")
        if metric is None:
            return candidates[0]
        return (max if mode == "max" else min)(
            candidates, key=lambda r: r.metrics.get(metric, float("inf") if mode == "min" else float("-inf"))
        )

    def get_dataframe(self):
        rows = []
        for r in self._results:
            row = dict(r.metrics)
            row["error"] = str(r.error) if r.error else None
            row["path"] = r.path
            rows.append(row)
        try:
            import pandas as pd

            return pd.DataFrame(rows)
        except ImportError:
            return rows
