"""Tuner: the trial control loop.

Parity: ``Tuner`` + ``TuneController`` (``python/ray/tune/execution/
tune_controller.py:68``; ``step:666``; trial actor scheduling ``:964``) —
trials are actors, reports stream back through a collector actor, the
scheduler may early-stop trials, results land in a ``ResultGrid``. Trainables
can be plain functions (``tune.report`` via the train session) or
``JaxTrainer`` instances (``trainer.as_trainable`` pattern,
``base_trainer.py:819``).
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.train._config import RunConfig
from ray_tpu.train import checkpointing
from ray_tpu.train._result import Result
from ray_tpu.train._session import TrainContext, _Session, _set_session
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler
from ray_tpu.tune.search import generate_variants
from ray_tpu.tune.tune_config import TuneConfig


@ray_tpu.remote(num_cpus=0)
class _TuneCollector:
    def __init__(self):
        self.reports: List = []

    def report(self, trial_id, iteration, metrics, ckpt_path):
        self.reports.append((trial_id, iteration, metrics, ckpt_path))
        return True

    def drain(self, start: int):
        return self.reports[start:]


@ray_tpu.remote
class _TrialActor:
    def __init__(self, trial_id: str, trial_dir: str):
        self.trial_id = trial_id
        self.trial_dir = trial_dir

    def run(self, fn_blob: bytes, config: dict, collector, ckpt_path=None):
        fn = cloudpickle.loads(fn_blob)
        ctx = TrainContext(world_rank=0, world_size=1, trial_dir=self.trial_dir)
        # resume routes through the checkpoint plane: a URI restores via the
        # digest-verified committed path (so a trial rescheduled onto
        # another node is not stuck chasing a dead node's local dir)
        initial = checkpointing.load_checkpoint(ckpt_path) if ckpt_path else None
        # step-plane records index under the trial id (every trial under
        # one shared "train" run would be unreadable)
        session = _Session(
            ctx, collector, initial, run_name=f"tune:{self.trial_id}"
        )
        # reports carry the trial id instead of a worker rank
        session.collector = _CollectorProxy(self.trial_id, collector)
        _set_session(session)
        try:
            return fn(config)
        finally:
            _set_session(None)
            # trial actors are killed right after their result: flush
            # buffered telemetry (checkpoint_save spans etc.) ahead of it
            from ray_tpu._private import telemetry

            telemetry.flush()


class _CollectorProxy:
    """Duck-types the collector ActorHandle: rewrites rank -> trial_id."""

    def __init__(self, trial_id: str, inner):
        self.trial_id = trial_id
        self.inner = inner

    @property
    def report(self):
        proxy = self

        class _M:
            def remote(self, rank, iteration, metrics, ckpt_path,
                       step_rec=None):
                if step_rec is not None:
                    # no BackendExecutor drains tune trials: step-plane
                    # records take the telemetry ring to the StepIndex
                    from ray_tpu._private import telemetry

                    telemetry.record_train_step(step_rec)
                return proxy.inner.report.remote(
                    proxy.trial_id, iteration, metrics, ckpt_path
                )

        return _M()


class Tuner:
    def __init__(
        self,
        trainable: Any,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    def _as_function(self) -> Callable:
        t = self.trainable
        if callable(t) and not hasattr(t, "fit"):
            return t
        # JaxTrainer-like: merge trial config into train_loop_config
        if hasattr(t, "train_loop"):
            def run_trainer(config):
                import copy
                import dataclasses

                from ray_tpu.train._session import get_context, report

                trainer = copy.copy(t)
                trainer.train_loop_config = {**(t.train_loop_config or {}), **config}
                # each trial gets its own storage dir — a shared inner
                # run_config would make concurrent trials prune each other's
                # checkpoints
                trial_dir = get_context().get_trial_dir()
                if trial_dir:
                    trainer.run_config = dataclasses.replace(
                        t.run_config, storage_path=trial_dir, name="trainer"
                    )
                result = trainer.fit()
                if result.error is not None:
                    raise result.error
                report(result.metrics)
            return run_trainer
        raise TypeError(f"unsupported trainable {type(t)}")

    # trials loaded by Tuner.restore (None = fresh experiment)
    _restored: Optional[dict] = None

    @classmethod
    def restore(cls, path: str, trainable: Any = None) -> "Tuner":
        """Resume an interrupted experiment from its state snapshot.

        Parity: ``Tuner.restore`` + the periodic experiment snapshot
        (``python/ray/tune/execution/experiment_state.py:1``). Unfinished
        trials are re-queued (from their last checkpoint when one exists);
        finished trials keep their results. ``path`` may be a local
        experiment dir or a ``scheme://`` URI — the snapshot and trial
        checkpoints are mirrored to external storage, so a driver on a
        fresh node can restore the whole experiment from the URI.
        """
        from ray_tpu._private import external_storage as _xstorage

        if _xstorage.has_scheme(path) and not path.startswith("file://"):
            blob = _xstorage.read_bytes(_xstorage.join(path, "experiment_state.pkl"))
            if blob is None:
                raise FileNotFoundError(f"no experiment_state.pkl under {path}")
            snap = cloudpickle.loads(blob)
        else:
            state_file = os.path.join(path, "experiment_state.pkl")
            with open(state_file, "rb") as fh:
                snap = cloudpickle.loads(fh.read())
        tuner = cls(
            trainable if trainable is not None else cloudpickle.loads(snap["fn_blob"]),
            param_space=snap["param_space"],
            tune_config=snap["tune_config"],
            run_config=snap["run_config"],
        )
        tuner._restored = snap
        return tuner

    @staticmethod
    def _snapshot(exp_dir, trials, fn_blob, param_space, tune_config, run_config,
                  exp_uri=None):
        snap = {
            "fn_blob": fn_blob,
            "param_space": param_space,
            "tune_config": tune_config,
            "run_config": run_config,
            "trials": {
                tid: {
                    "config": t["config"],
                    "state": t["state"],
                    "iteration": t["iteration"],
                    "last_metrics": t["last_metrics"],
                    "checkpoint_path": t["checkpoint"].path if t["checkpoint"] else None,
                    "checkpoint_uri": t.get("checkpoint_uri"),
                    "dir": t["dir"],
                }
                for tid, t in trials.items()
            },
        }
        blob = cloudpickle.dumps(snap)
        tmp = os.path.join(exp_dir, ".experiment_state.tmp")
        with open(tmp, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, os.path.join(exp_dir, "experiment_state.pkl"))
        if exp_uri is not None:
            # mirror the snapshot next to the mirrored trial checkpoints so
            # Tuner.restore(uri) works from any node (backend writes are
            # atomic per object)
            from ray_tpu._private import external_storage as _xstorage

            try:
                _xstorage.write_bytes(
                    _xstorage.join(exp_uri, "experiment_state.pkl"), blob
                )
            except Exception:
                pass  # next periodic snapshot retries

    def fit(self) -> ResultGrid:
        from ray_tpu._private import external_storage as _xstorage

        cfg = self.tune_config
        exp_name = self.run_config.name or f"tune_{time.strftime('%Y%m%d_%H%M%S')}"
        # external storage: trials stage locally, every checkpoint is
        # committed out through a per-trial CheckpointManager and the
        # experiment snapshot is mirrored beside them
        exp_dir, exp_uri = checkpointing.resolve_staging(
            self.run_config.resolved_storage_path(), exp_name, kind="tune"
        )
        os.makedirs(exp_dir, exist_ok=True)
        ckpt_managers: Dict[str, checkpointing.CheckpointManager] = {}

        scheduler = cfg.scheduler or FIFOScheduler()
        fn_blob = cloudpickle.dumps(self._as_function())
        collector = _TuneCollector.remote()

        from ray_tpu.tune.logger import TrialLoggers
        from ray_tpu.tune.stopper import coerce_stopper

        stopper = coerce_stopper(self.run_config.stop)
        loggers = TrialLoggers()
        search_alg = cfg.search_alg
        if search_alg is not None:
            search_alg.set_search_space(self.param_space)

        max_conc = cfg.max_concurrent_trials or max(
            1, int(ray_tpu.cluster_resources().get("CPU", 1))
        )

        trials: Dict[str, dict] = {}
        queue = []
        if self._restored is not None:
            for tid, st in self._restored["trials"].items():
                ckpt = Checkpoint(st["checkpoint_path"]) if st["checkpoint_path"] else None
                # prefer the node-local copy when it survived; fall back to
                # the committed URI (the restore-on-another-node path); a
                # dead local path with no mirror restarts from scratch
                resume_from = st["checkpoint_path"]
                if not (resume_from and os.path.isdir(resume_from)):
                    resume_from = st.get("checkpoint_uri")
                trials[tid] = {
                    "config": st["config"],
                    "state": st["state"],
                    "actor": None,
                    "ref": None,
                    "last_metrics": st["last_metrics"],
                    "iteration": st["iteration"],
                    "checkpoint": ckpt,
                    "checkpoint_uri": st.get("checkpoint_uri"),
                    "error": None,
                    "dir": st["dir"],
                    "resume_from": resume_from,
                }
                if st["state"] in ("PENDING", "RUNNING"):
                    trials[tid]["state"] = "PENDING"
                    queue.append(tid)
                elif search_alg is not None and hasattr(search_alg, "observe"):
                    # re-feed finished trials so the restored search model
                    # isn't empty (suggest-time vectors died with the driver)
                    search_alg.observe(st["config"] or {}, st["last_metrics"])
        else:
            if search_alg is not None:
                # configs are suggested lazily at launch time so later trials
                # benefit from earlier results (sequential model-based search)
                variants = [None] * cfg.num_samples
            else:
                variants = generate_variants(self.param_space, cfg.num_samples, cfg.seed)
            for i, variant in enumerate(variants):
                tid = f"trial_{i:05d}_{uuid.uuid4().hex[:4]}"
                trials[tid] = {
                    "config": variant,
                    "state": "PENDING",
                    "actor": None,
                    "ref": None,
                    "last_metrics": {},
                    "iteration": 0,
                    "checkpoint": None,
                    "checkpoint_uri": None,
                    "error": None,
                    "dir": os.path.join(exp_dir, tid),
                    "resume_from": None,
                }
                queue.append(tid)

        running: Dict[Any, str] = {}  # ref -> trial_id
        seen = 0
        last_snap = 0.0

        def launch(tid):
            t = trials[tid]
            if t["config"] is None:
                t["config"] = search_alg.suggest(tid)
            os.makedirs(t["dir"], exist_ok=True)
            resume = t.get("resume_from")
            if resume and _xstorage.has_scheme(resume) and not resume.startswith("file://"):
                # materialize the committed checkpoint driver-side (the
                # driver holds the backend registrations; workers get a
                # digest-verified local directory). If the exact step the
                # snapshot recorded never committed (driver died mid-upload)
                # fall back to the trial's newest committed step.
                try:
                    resume = checkpointing.load_checkpoint(resume).path
                except (FileNotFoundError, _xstorage.IntegrityError):
                    ckpt = checkpointing.latest_checkpoint(resume.rsplit("/", 1)[0])
                    resume = ckpt.path if ckpt is not None else None
            actor = _TrialActor.remote(tid, t["dir"])
            ref = actor.run.remote(fn_blob, t["config"], collector, resume)
            t.update(state="RUNNING", actor=actor, ref=ref)
            running[ref] = tid

        def exploit(tid):
            """PBT: clone a top trial's config+checkpoint, mutate, relaunch."""
            t = trials[tid]
            src_tid = scheduler.choose_exploit_source(tid, trials)
            if src_tid is None:
                return
            src = trials[src_tid]
            if t["actor"] is not None:
                ray_tpu.kill(t["actor"])
            running.pop(t["ref"], None)
            t["config"] = scheduler.mutate_config(src["config"])
            t["resume_from"] = src["checkpoint"].path if src["checkpoint"] else None
            t["state"] = "PENDING"
            t["actor"] = t["ref"] = None
            queue.append(tid)

        while queue or running:
            while queue and len(running) < max_conc:
                launch(queue.pop(0))
            ready, _ = ray_tpu.wait(list(running.keys()), num_returns=1, timeout=0.5)
            # drain reports and apply the scheduler
            new = ray_tpu.get(collector.drain.remote(seen), timeout=60)
            seen += len(new)
            for tid, iteration, metrics, ckpt_path in new:
                t = trials.get(tid)
                if t is None or t["state"] in ("TERMINATED", "ERROR", "STOPPED"):
                    continue
                t["last_metrics"] = metrics
                t["iteration"] = iteration
                if ckpt_path:
                    t["checkpoint"] = Checkpoint(ckpt_path)
                    if exp_uri is not None:
                        # commit the trial checkpoint to external storage
                        # through the plane (async, digest-verified): the
                        # URI is what a restore on another node resumes from
                        mgr = ckpt_managers.get(tid)
                        if mgr is None:
                            mgr = ckpt_managers[tid] = checkpointing.CheckpointManager(
                                t["dir"],
                                storage_uri=_xstorage.join(exp_uri, tid),
                                world_size=1,
                                keep=self.run_config.checkpoint_config.num_to_keep,
                                run_name=f"{exp_name}/{tid}",
                            )
                        step = checkpointing.parse_step(os.path.basename(ckpt_path))
                        if step is not None:
                            mgr.note_shard(0, step, ckpt_path, metrics=metrics)
                            t["checkpoint_uri"] = _xstorage.join(
                                exp_uri, tid, checkpointing.step_dir_name(step)
                            )
                logged = {**metrics, "training_iteration": iteration,
                          "trial_id": tid}
                loggers.log_result(tid, t["dir"], logged)
                verdict = scheduler.on_result(tid, iteration, metrics)
                if stopper is not None and verdict == CONTINUE and stopper(tid, logged):
                    verdict = STOP
                    if stopper.stop_all():
                        # stop every other live trial too
                        for otid, ot in trials.items():
                            if otid != tid and ot["state"] == "RUNNING":
                                ot["state"] = "STOPPED"
                                if ot["actor"] is not None:
                                    ray_tpu.kill(ot["actor"])
                                running.pop(ot["ref"], None)
                                if search_alg is not None:
                                    search_alg.on_trial_complete(
                                        otid, ot["last_metrics"]
                                    )
                        # queued trials never ran: drop them (lazily-suggested
                        # ones have no config yet and would surface as phantom
                        # empty rows in the ResultGrid)
                        for qtid in queue:
                            trials.pop(qtid, None)
                        queue.clear()
                if verdict == STOP:
                    t["state"] = "STOPPED"
                    if t["actor"] is not None:
                        ray_tpu.kill(t["actor"])
                    running.pop(t["ref"], None)
                    if search_alg is not None:
                        search_alg.on_trial_complete(tid, t["last_metrics"])
                elif verdict == "EXPLOIT":
                    exploit(tid)
            for ref in ready:
                tid = running.pop(ref, None)
                if tid is None:
                    continue
                t = trials[tid]
                if t["state"] == "PENDING":
                    continue  # relaunched via exploit
                try:
                    ray_tpu.get(ref)
                    t["state"] = "TERMINATED"
                except exc.ActorDiedError:
                    if t["state"] != "STOPPED":
                        t["state"] = "ERROR"
                        t["error"] = exc.ActorDiedError(reason="trial actor died")
                except Exception as e:  # noqa: BLE001
                    t["state"] = "ERROR"
                    t["error"] = e
                if t["actor"] is not None and t["state"] != "STOPPED":
                    ray_tpu.kill(t["actor"])
                if search_alg is not None and t["state"] in ("TERMINATED", "ERROR"):
                    search_alg.on_trial_complete(tid, t["last_metrics"])
            now = time.monotonic()
            if now - last_snap > 2.0:
                last_snap = now
                self._snapshot(
                    exp_dir, trials, fn_blob, self.param_space,
                    self.tune_config, self.run_config, exp_uri=exp_uri,
                )
        # drain the per-trial checkpoint managers BEFORE the final snapshot,
        # so the snapshot's checkpoint_uri entries are all committed
        for mgr in ckpt_managers.values():
            mgr.wait(timeout=60.0)
            mgr.shutdown()
        self._snapshot(
            exp_dir, trials, fn_blob, self.param_space,
            self.tune_config, self.run_config, exp_uri=exp_uri,
        )
        loggers.close()

        results = []
        for tid, t in trials.items():
            metrics = dict(t["last_metrics"])
            metrics["config"] = t["config"]
            metrics["training_iteration"] = t["iteration"]
            metrics["trial_id"] = tid
            results.append(
                Result(
                    metrics=metrics,
                    checkpoint=t["checkpoint"],
                    path=t["dir"],
                    error=t["error"],
                )
            )
        return ResultGrid(results)


def with_parameters(trainable, **kwargs):
    """Bind large constant objects to a trainable once (parity:
    ``tune.with_parameters``): each object is stored in the cluster object
    store a single time and every trial fetches it by reference, instead of
    re-pickling the payload into each trial's function blob."""
    import functools

    import ray_tpu

    refs = {k: ray_tpu.put(v) for k, v in kwargs.items()}

    @functools.wraps(trainable)
    def inner(config):
        resolved = {k: ray_tpu.get(r, timeout=600) for k, r in refs.items()}
        return trainable(config, **resolved)

    return inner
