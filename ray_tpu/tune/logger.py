"""Trial result loggers: result.json (JSONL) + progress.csv per trial.

Parity: ``python/ray/tune/logger/`` — the reference writes ``result.json``
and ``progress.csv`` into every trial dir by default (CSV/JSON logger
callbacks); TensorBoard is a third sink when available. Loggers here are
driver-side (results already stream to the controller), writing line-at-a-time
so a crashed experiment keeps everything reported so far.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, Optional


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return repr(v)


class TrialLoggers:
    """One instance per experiment; fans each result out to per-trial files."""

    def __init__(self):
        self._csv_writers: Dict[str, tuple] = {}  # tid -> (fh, writer, fields)

    def log_result(self, trial_id: str, trial_dir: str, result: Dict[str, Any]):
        os.makedirs(trial_dir, exist_ok=True)
        flat = {k: _jsonable(v) for k, v in result.items()}
        with open(os.path.join(trial_dir, "result.json"), "a") as fh:
            fh.write(json.dumps(flat) + "\n")
        entry = self._csv_writers.get(trial_id)
        if entry is None:
            fields = list(flat)
            fh = open(os.path.join(trial_dir, "progress.csv"), "a", newline="")
            writer = csv.DictWriter(fh, fieldnames=fields, extrasaction="ignore")
            if fh.tell() == 0:
                writer.writeheader()
            entry = (fh, writer, fields)
            self._csv_writers[trial_id] = entry
        fh, writer, fields = entry
        writer.writerow({k: flat.get(k, "") for k in fields})
        fh.flush()

    def close(self):
        for fh, _, _ in self._csv_writers.values():
            try:
                fh.close()
            except OSError:
                pass
        self._csv_writers.clear()
