"""Stoppers: experiment/trial-level stop criteria.

Parity: ``python/ray/tune/stopper/`` — ``Stopper.__call__(trial_id, result)``
returns True to stop the trial; ``stop_all()`` ends the experiment.
``RunConfig(stop=...)`` accepts a Stopper, a dict of metric thresholds, or a
callable.
"""

from __future__ import annotations

from collections import defaultdict, deque
from typing import Any, Callable, Dict, Optional


class Stopper:
    def __call__(self, trial_id: str, result: Dict[str, Any]) -> bool:
        raise NotImplementedError

    def stop_all(self) -> bool:
        return False


class MaximumIterationStopper(Stopper):
    def __init__(self, max_iter: int):
        self._max_iter = max_iter

    def __call__(self, trial_id, result):
        return result.get("training_iteration", 0) >= self._max_iter


class TrialPlateauStopper(Stopper):
    """Stop a trial when its metric stops improving: std of the last
    ``num_results`` values falls at or below ``std`` (parity:
    ``tune/stopper/trial_plateau.py``)."""

    def __init__(self, metric: str, *, std: float = 0.01, num_results: int = 4,
                 grace_period: int = 4):
        self._metric = metric
        self._std = std
        self._num_results = num_results
        self._grace = grace_period
        self._history: Dict[str, deque] = defaultdict(
            lambda: deque(maxlen=num_results)
        )
        self._iters: Dict[str, int] = defaultdict(int)

    def __call__(self, trial_id, result):
        if self._metric not in result:
            return False
        self._iters[trial_id] += 1
        h = self._history[trial_id]
        h.append(float(result[self._metric]))
        if self._iters[trial_id] < self._grace or len(h) < self._num_results:
            return False
        import numpy as np

        return float(np.std(h)) <= self._std


class FunctionStopper(Stopper):
    def __init__(self, fn: Callable[[str, Dict[str, Any]], bool]):
        self._fn = fn

    def __call__(self, trial_id, result):
        return bool(self._fn(trial_id, result))


class MetricThresholdStopper(Stopper):
    """dict-form stop criteria: {"metric": threshold} stops a trial once
    metric >= threshold (or training_iteration >= threshold)."""

    def __init__(self, thresholds: Dict[str, float]):
        self._thresholds = dict(thresholds)

    def __call__(self, trial_id, result):
        for metric, bound in self._thresholds.items():
            if metric in result and float(result[metric]) >= float(bound):
                return True
        return False


class CombinedStopper(Stopper):
    def __init__(self, *stoppers: Stopper):
        self._stoppers = stoppers

    def __call__(self, trial_id, result):
        return any(s(trial_id, result) for s in self._stoppers)

    def stop_all(self):
        return any(s.stop_all() for s in self._stoppers)


def coerce_stopper(stop) -> Optional[Stopper]:
    """RunConfig.stop -> Stopper (dict / callable / Stopper / None)."""
    if stop is None:
        return None
    if isinstance(stop, Stopper):
        return stop
    if isinstance(stop, dict):
        return MetricThresholdStopper(stop)
    if callable(stop):
        return FunctionStopper(stop)
    raise TypeError(f"unsupported stop criteria: {type(stop)}")
