"""Search spaces and variant generation.

Parity: ``python/ray/tune/search/`` — ``grid_search`` + sampling domains
(``sample.py``) and the ``BasicVariantGenerator`` cross-product expansion
(``search/basic_variant.py``).
"""

from __future__ import annotations

import itertools
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional


@dataclass
class Domain:
    sampler: Callable[[random.Random], Any]

    def sample(self, rng: random.Random) -> Any:
        return self.sampler(rng)


def uniform(low: float, high: float) -> Domain:
    return Domain(lambda rng: rng.uniform(low, high))


def loguniform(low: float, high: float) -> Domain:
    import math

    return Domain(lambda rng: math.exp(rng.uniform(math.log(low), math.log(high))))


def randint(low: int, high: int) -> Domain:
    return Domain(lambda rng: rng.randrange(low, high))


def qrandint(low: int, high: int, q: int) -> Domain:
    return Domain(lambda rng: (rng.randrange(low, high) // q) * q)


def randn(mean: float = 0.0, sd: float = 1.0) -> Domain:
    return Domain(lambda rng: rng.gauss(mean, sd))


def choice(options: List[Any]) -> Domain:
    opts = list(options)
    return Domain(lambda rng: rng.choice(opts))


@dataclass
class GridSearch:
    values: List[Any]


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(list(values))


def generate_variants(
    param_space: Dict[str, Any], num_samples: int, seed: Optional[int] = None
) -> List[Dict[str, Any]]:
    """Expand grid axes into a cross product; sample Domains num_samples times
    per grid point (parity: BasicVariantGenerator semantics)."""
    rng = random.Random(seed)
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grid_values = [param_space[k].values for k in grid_keys]
    variants = []
    grid_points = list(itertools.product(*grid_values)) if grid_keys else [()]
    for point in grid_points:
        for _ in range(num_samples):
            cfg = {}
            for k, v in param_space.items():
                if isinstance(v, GridSearch):
                    cfg[k] = point[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants
