"""Hyperparameter tuning library (Ray Tune equivalent).

Parity: ``python/ray/tune`` — ``Tuner`` /
``TuneController`` event loop (``execution/tune_controller.py:68``) managing
trial actors, search algorithms (``search/``), trial schedulers
(``schedulers/``: ASHA, median stopping), ``ResultGrid``. Trials are plain
actors of this framework's core (libraries stay pure clients, SURVEY.md §1).
"""

from ray_tpu.tune.search import (
    choice,
    grid_search,
    loguniform,
    qrandint,
    randint,
    randn,
    uniform,
)
from ray_tpu.tune import bayesopt
from ray_tpu.tune.bayesopt import BayesOptSearch
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import PopulationBasedTraining, ASHAScheduler, FIFOScheduler, HyperBandScheduler, MedianStoppingRule
from ray_tpu.tune.stopper import (
    CombinedStopper,
    FunctionStopper,
    MaximumIterationStopper,
    Stopper,
    TrialPlateauStopper,
)
from ray_tpu.tune.tune_config import TuneConfig
from ray_tpu.tune.tuner import Tuner, with_parameters

__all__ = [
    "Tuner",
    "with_parameters",
    "TuneConfig",
    "ResultGrid",
    "BayesOptSearch",
    "bayesopt",
    "Stopper",
    "MaximumIterationStopper",
    "TrialPlateauStopper",
    "FunctionStopper",
    "CombinedStopper",
    "grid_search",
    "choice",
    "uniform",
    "loguniform",
    "randint",
    "qrandint",
    "randn",
    "FIFOScheduler",
    "ASHAScheduler",
    "HyperBandScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
]

from ray_tpu._private import usage as _usage

_usage.record_library_usage("tune")
del _usage
