"""BayesOptSearch: Gaussian-process search with expected improvement.

Parity: the role of ``python/ray/tune/search/bayesopt/`` (which wraps the
external ``bayesian-optimization`` package). Implemented natively on numpy:
an RBF-kernel GP posterior over the observed (config, objective) pairs and
candidate ranking by expected improvement. Continuous domains
(uniform/loguniform/randint/qrandint) are modeled in a normalized unit cube;
``choice`` axes are sampled uniformly (categorical kernels are out of scope,
matching the wrapped package's behavior of encoding them numerically).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search import Domain, GridSearch


class _Axis:
    """A continuous parameter axis mapped to [0, 1]."""

    def __init__(self, name: str, low: float, high: float, *, log: bool,
                 integer: bool, q: int = 1):
        self.name = name
        self.low = low
        self.high = high
        self.log = log
        self.integer = integer
        self.q = q

    def to_unit(self, v: float) -> float:
        lo, hi = self.low, self.high
        if self.log:
            return (math.log(v) - math.log(lo)) / (math.log(hi) - math.log(lo))
        return (v - lo) / (hi - lo)

    def from_unit(self, u: float) -> Any:
        lo, hi = self.low, self.high
        if self.log:
            v = math.exp(math.log(lo) + u * (math.log(hi) - math.log(lo)))
        else:
            v = lo + u * (hi - lo)
        if self.integer:
            v = int(round(v / self.q) * self.q)
            v = max(int(lo), min(int(hi), v))
        return v


def _classify_axes(param_space: Dict[str, Any]) -> Tuple[List[_Axis], Dict[str, Any]]:
    """Split the space into GP-modeled axes and passthrough entries."""
    axes: List[_Axis] = []
    passthrough: Dict[str, Any] = {}
    for name, dom in param_space.items():
        meta = getattr(dom, "_bayes_meta", None)
        if isinstance(dom, GridSearch):
            raise ValueError("BayesOptSearch does not support grid_search axes")
        if meta is not None:
            axes.append(_Axis(name, **meta))
        else:
            passthrough[name] = dom
    return axes, passthrough


# Domains advertise their bounds for the GP through _bayes_meta; patching the
# constructors here keeps search.py dependency-free.
def uniform(low: float, high: float) -> Domain:
    d = Domain(lambda rng: rng.uniform(low, high))
    d._bayes_meta = dict(low=low, high=high, log=False, integer=False)
    return d


def loguniform(low: float, high: float) -> Domain:
    d = Domain(lambda rng: math.exp(rng.uniform(math.log(low), math.log(high))))
    d._bayes_meta = dict(low=low, high=high, log=True, integer=False)
    return d


def randint(low: int, high: int) -> Domain:
    d = Domain(lambda rng: rng.randrange(low, high))
    d._bayes_meta = dict(low=low, high=high - 1, log=False, integer=True)
    return d


class BayesOptSearch:
    def __init__(self, *, metric: str, mode: str = "max",
                 n_initial_points: int = 5, n_candidates: int = 256,
                 length_scale: float = 0.25, noise: float = 1e-4,
                 seed: Optional[int] = None):
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.metric = metric
        self.mode = mode
        self.n_initial = n_initial_points
        self.n_candidates = n_candidates
        self.length_scale = length_scale
        self.noise = noise
        self._rng = random.Random(seed)
        self._np_rng = np.random.default_rng(seed)
        self._axes: Optional[List[_Axis]] = None
        self._passthrough: Dict[str, Any] = {}
        self._pending: Dict[str, np.ndarray] = {}  # tid -> unit vector
        self._X: List[np.ndarray] = []
        self._y: List[float] = []

    def set_search_space(self, param_space: Dict[str, Any]):
        self._axes, self._passthrough = _classify_axes(param_space)
        if not self._axes:
            raise ValueError(
                "BayesOptSearch needs at least one bayesopt.uniform/"
                "loguniform/randint axis in param_space"
            )
        # fresh model per fit(): Tuner.restore re-feeds finished trials via
        # observe(), so carrying pickled observations would double-count
        self._pending.clear()
        self._X = []
        self._y = []

    def _sample_passthrough(self) -> Dict[str, Any]:
        out = {}
        for name, dom in self._passthrough.items():
            out[name] = dom.sample(self._rng) if isinstance(dom, Domain) else dom
        return out

    def _vec_to_config(self, u: np.ndarray) -> Dict[str, Any]:
        cfg = {ax.name: ax.from_unit(float(u[i])) for i, ax in enumerate(self._axes)}
        cfg.update(self._sample_passthrough())
        return cfg

    # -- GP machinery ------------------------------------------------------

    def _kernel(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / self.length_scale**2)

    def _posterior(self, Xc: np.ndarray):
        X = np.stack(self._X)
        y = np.asarray(self._y)
        if self.mode == "min":
            y = -y
        y_mean, y_std = y.mean(), y.std() + 1e-9
        yn = (y - y_mean) / y_std
        K = self._kernel(X, X) + self.noise * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
        Ks = self._kernel(Xc, X)
        mu = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)
        var = np.clip(1.0 - (v**2).sum(0), 1e-12, None)
        return mu, np.sqrt(var), yn.max()

    def _expected_improvement(self, mu, sigma, best):
        z = (mu - best) / sigma
        Phi = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2.0)))
        phi = np.exp(-0.5 * z**2) / math.sqrt(2.0 * math.pi)
        return (mu - best) * Phi + sigma * phi

    # -- searcher protocol -------------------------------------------------

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        if self._axes is None:
            raise RuntimeError("set_search_space was not called")
        dim = len(self._axes)
        if len(self._X) < self.n_initial:
            u = self._np_rng.random(dim)
        else:
            cand = self._np_rng.random((self.n_candidates, dim))
            mu, sigma, best = self._posterior(cand)
            u = cand[int(np.argmax(self._expected_improvement(mu, sigma, best)))]
        self._pending[trial_id] = u
        return self._vec_to_config(u)

    def on_trial_complete(self, trial_id: str, result: Optional[Dict[str, Any]]):
        u = self._pending.pop(trial_id, None)
        if u is None or not result or self.metric not in result:
            return
        self._X.append(u)
        self._y.append(float(result[self.metric]))

    def observe(self, config: Dict[str, Any], result: Optional[Dict[str, Any]]):
        """Feed a finished (config, result) pair whose suggest-time vector is
        unavailable — e.g. trials reloaded by ``Tuner.restore``. The unit
        vector is reconstructed from the config via the axis mappings."""
        if self._axes is None or not result or self.metric not in result:
            return
        try:
            u = np.array(
                [ax.to_unit(float(config[ax.name])) for ax in self._axes]
            )
        except (KeyError, TypeError, ValueError):
            return
        self._X.append(np.clip(u, 0.0, 1.0))
        self._y.append(float(result[self.metric]))
