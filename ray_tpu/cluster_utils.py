"""Multi-node cluster fixture: real node-daemon processes on one machine.

Parity: ``python/ray/cluster_utils.py:135`` (``Cluster``, ``add_node:201``) —
the fixture the reference uses to test "multi-node" without a cluster: real
raylet processes, real sockets, fake machines. ``add_node`` spawns a real
``ray_tpu._private.raylet`` daemon process (own worker pool, own object
store, object server for peer pulls) registered with the head over TCP.
``add_node(virtual=True)`` keeps the cheaper in-scheduler resource-ledger
node for tests that only exercise placement math.
"""

from __future__ import annotations

import atexit
import json
import os
import subprocess
import sys
import time
from typing import Dict, Optional

import ray_tpu
from ray_tpu._private.ids import NodeID
from ray_tpu._private.worker import get_driver


class ClusterNode:
    def __init__(self, node_id: Optional[NodeID], cluster: "Cluster", proc=None):
        self.node_id = node_id
        self.proc = proc  # subprocess.Popen for real daemon nodes
        self._cluster = cluster

    @property
    def hex(self) -> str:
        return self.node_id.hex() if self.node_id else ""


# backwards-compat alias (round-1 name)
VirtualNode = ClusterNode


def spawn_daemon_process(
    driver,
    *,
    num_cpus: float = 1.0,
    num_tpus: float = 0.0,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    wait: bool = True,
    timeout: float = 30.0,
):
    """Spawn one real node-daemon process attached to the driver's head.

    The single spawn protocol shared by the test Cluster fixture and the
    autoscaler's LocalDaemonNodeProvider. Returns (Popen, node_id_hex|None).
    """
    import uuid

    host, port = driver.node.start_head_server()
    env = dict(os.environ)
    env["RAY_TPU_AUTH"] = driver.config.cluster_auth_key
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    # a unique label identifies THIS spawn exactly (set-difference against a
    # before-snapshot mis-attributes nodes when two spawns overlap)
    token = uuid.uuid4().hex[:12]
    all_labels = dict(labels or {})
    all_labels["spawn-token"] = token
    proc = subprocess.Popen(
        [
            sys.executable,
            "-m",
            "ray_tpu._private.raylet",
            "--address",
            f"{host}:{port}",
            "--num-cpus",
            str(num_cpus),
            "--num-tpus",
            str(num_tpus),
            "--resources",
            json.dumps(resources or {}),
            "--labels",
            json.dumps(all_labels),
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=(
            None
            if os.environ.get("RAY_TPU_DAEMON_STDERR")
            else subprocess.DEVNULL
        ),
    )
    if not wait:
        return proc, None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        fresh = [
            n
            for n in ray_tpu.nodes()
            if n["alive"] and n.get("labels", {}).get("spawn-token") == token
        ]
        if fresh:
            return proc, fresh[0]["node_id"]
        if proc.poll() is not None:
            raise RuntimeError(
                f"node daemon exited rc={proc.returncode} before registering"
            )
        time.sleep(0.02)
    proc.kill()
    raise TimeoutError(f"node daemon did not register within {timeout}s")


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        head_node_args: Optional[dict] = None,
        connect: bool = True,
    ):
        self._nodes = []
        self._procs = []
        self.head_node: Optional[ClusterNode] = None
        self.address = None
        if initialize_head:
            rt = ray_tpu.init(**(head_node_args or {}))
            self.address = rt.node.start_head_server()
            self.head_node = ClusterNode(rt.node.head_node_id, self)
            self._nodes.append(self.head_node)
        atexit.register(self._atexit)

    def add_node(
        self,
        num_cpus: float = 1.0,
        num_tpus: float = 0.0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        virtual: bool = False,
        wait: bool = True,
        **_ignored,
    ) -> ClusterNode:
        driver = get_driver()
        if virtual:
            nid = driver.node.add_virtual_node(
                num_cpus=num_cpus, num_tpus=num_tpus, resources=resources, labels=labels
            )
            node = ClusterNode(nid, self)
            self._nodes.append(node)
            return node

        proc, node_id_hex = spawn_daemon_process(
            driver,
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=resources,
            labels=labels,
            wait=wait,
        )
        self._procs.append(proc)
        node = ClusterNode(
            NodeID.from_hex(node_id_hex) if node_id_hex else None, self, proc=proc
        )
        self._nodes.append(node)
        return node

    def remove_node(self, node: ClusterNode, allow_graceful: bool = True) -> None:
        if node.proc is not None:
            # kill -9 the daemon: the head sees the socket drop and declares
            # the node dead (the reference kills raylets the same way,
            # python/ray/_private/test_utils.py:1549)
            node.proc.kill()
            node.proc.wait(timeout=10)
        else:
            get_driver().node.remove_virtual_node(node.node_id)
        self._nodes.remove(node)

    def wait_for_nodes(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        want = len(self._nodes)
        while time.monotonic() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            if len(alive) >= want:
                return
            time.sleep(0.01)
        raise TimeoutError("nodes did not register")

    def shutdown(self) -> None:
        ray_tpu.shutdown()
        self._reap()

    def _reap(self):
        for proc in self._procs:
            if proc.poll() is None:
                proc.terminate()
        deadline = time.monotonic() + 3
        for proc in self._procs:
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
        self._procs.clear()

    def _atexit(self):
        try:
            self._reap()
        except Exception:
            pass
