"""Virtual multi-node cluster for tests.

Parity: ``python/ray/cluster_utils.py:135`` (``Cluster``, ``add_node:201``) —
the fixture that makes "multi-node" testable on one machine. Nodes here are
virtual resource ledgers inside the single scheduler; workers are real
processes tagged with their node, so scheduling policies, spillback, placement
groups and node-failure handling are all exercised for real.
"""

from __future__ import annotations

from typing import Dict, Optional

import ray_tpu
from ray_tpu._private.ids import NodeID
from ray_tpu._private.worker import get_driver


class VirtualNode:
    def __init__(self, node_id: NodeID, cluster: "Cluster"):
        self.node_id = node_id
        self._cluster = cluster

    @property
    def hex(self) -> str:
        return self.node_id.hex()


class Cluster:
    def __init__(
        self,
        initialize_head: bool = True,
        head_node_args: Optional[dict] = None,
        connect: bool = True,
    ):
        self._nodes = []
        self.head_node: Optional[VirtualNode] = None
        if initialize_head:
            rt = ray_tpu.init(**(head_node_args or {}))
            self.head_node = VirtualNode(rt.node.head_node_id, self)
            self._nodes.append(self.head_node)

    def add_node(
        self,
        num_cpus: float = 1.0,
        num_tpus: float = 0.0,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        **_ignored,
    ) -> VirtualNode:
        driver = get_driver()
        nid = driver.node.add_virtual_node(
            num_cpus=num_cpus, num_tpus=num_tpus, resources=resources, labels=labels
        )
        node = VirtualNode(nid, self)
        self._nodes.append(node)
        return node

    def remove_node(self, node: VirtualNode, allow_graceful: bool = True) -> None:
        driver = get_driver()
        driver.node.remove_virtual_node(node.node_id)
        self._nodes.remove(node)

    def wait_for_nodes(self, timeout: float = 10.0) -> None:
        import time

        deadline = time.monotonic() + timeout
        want = len(self._nodes)
        while time.monotonic() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["alive"]]
            if len(alive) >= want:
                return
            time.sleep(0.01)
        raise TimeoutError("nodes did not register")

    def shutdown(self) -> None:
        ray_tpu.shutdown()
