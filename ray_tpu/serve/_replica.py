"""Replica actor: wraps the user's deployment callable.

Parity: ``python/ray/serve/_private/replica.py`` — executes requests against
the user class/function; threaded (``max_concurrency = max_ongoing_requests``)
so concurrent requests overlap; exposes a health-check probe.
"""

from __future__ import annotations

from typing import Any, Dict, List

import cloudpickle

import ray_tpu


@ray_tpu.remote
class Replica:
    def __init__(self, callable_blob: bytes, init_args, init_kwargs):
        # nested DeploymentHandles (model composition) arrive pre-resolved
        # inside init_args/kwargs
        target = cloudpickle.loads(callable_blob)
        if isinstance(target, type):
            self._callable = target(*init_args, **init_kwargs)
        elif init_args or init_kwargs:
            import functools

            self._callable = functools.partial(target, *init_args, **init_kwargs)
        else:
            self._callable = target

    def handle_request(self, method: str, args: List, kwargs: Dict):
        if method == "__call__":
            return self._callable(*args, **kwargs)
        return getattr(self._callable, method)(*args, **kwargs)

    def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if callable(user_check):
            user_check()
        return True
