"""Replica actor: wraps the user's deployment callable.

Parity: ``python/ray/serve/_private/replica.py`` — executes requests against
the user class/function; threaded so concurrent requests overlap, with an
internal gate at ``max_ongoing_requests`` so the entered-thread count is a
true queued+running depth (the autoscaling metric,
``_private/autoscaling_state.py``); streaming responses via generator
methods (``_private/proxy_response_generator.py``); model multiplexing via a
per-replica LRU (``python/ray/serve/multiplex.py:1``).
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List

import cloudpickle

import ray_tpu

_request_ctx = threading.local()

# replica-side telemetry (parity: serve's autoscaling/latency metrics,
# ray_serve_replica_processing_queries / ray_serve_deployment_processing_
# latency_ms). Lazy module-level singletons: records are local dict updates
# batched by the telemetry plane — cheap enough for the request hot path.
_metrics: dict = {}


def _replica_metrics() -> dict:
    if not _metrics:
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        _metrics["queue_depth"] = Gauge(
            "ray_tpu_serve_replica_queue_depth",
            "queued + running requests on one replica (autoscaling metric)",
            tag_keys=("deployment",),
        )
        _metrics["latency"] = Histogram(
            "ray_tpu_serve_request_latency_ms",
            "end-to-end request execution latency per deployment",
            # default sub-ms..10s grid (metrics.DEFAULT_HISTOGRAM_BOUNDARIES)
            # so fast direct-path requests resolve; override per metric via
            # configure_histogram_boundaries or RAY_TPU_HIST_BUCKETS_*
            tag_keys=("deployment", "method"),
        )
        _metrics["requests"] = Counter(
            "ray_tpu_serve_requests_total",
            "requests executed per deployment",
            tag_keys=("deployment", "method"),
        )
        _metrics["ttft"] = Histogram(
            "ray_tpu_serve_ttft_ms",
            "streaming time-to-first-token per deployment (request "
            "admitted -> first item yielded) — the stream-TTFT SLO input",
            tag_keys=("deployment", "method"),
        )
    return _metrics


def get_multiplexed_model_id() -> str:
    """Parity: ``serve.get_multiplexed_model_id`` — valid inside a request."""
    return getattr(_request_ctx, "multiplexed_model_id", "")


class _MultiplexCache:
    """Per-replica LRU of loaded models (parity: _ModelMultiplexWrapper)."""

    def __init__(self, loader, max_models: int):
        self._loader = loader
        self._max = max_models
        self._models: "collections.OrderedDict[str, Any]" = collections.OrderedDict()
        self._lock = threading.Lock()

    def get(self, model_id: str):
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
        model = self._loader(model_id)
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            while len(self._models) > self._max:
                self._models.popitem(last=False)
        return model

    def model_ids(self) -> List[str]:
        with self._lock:
            return list(self._models)


def multiplexed(func=None, *, max_num_models_per_replica: int = 3):
    """Decorator wrapping a model-loader method with a per-replica LRU
    (parity: ``serve.multiplexed``): ``self.get_model(model_id)`` loads at
    most once per cached model and evicts beyond the limit."""

    def wrap(f):
        import functools

        @functools.wraps(f)
        def wrapper(owner, model_id):
            caches = getattr(owner, "__serve_mux_caches__", None)
            if caches is None:
                caches = {}
                object.__setattr__(owner, "__serve_mux_caches__", caches)
            cache = caches.get(f.__name__)
            if cache is None:
                cache = caches[f.__name__] = _MultiplexCache(
                    lambda mid: f(owner, mid), max_num_models_per_replica
                )
            return cache.get(model_id)

        wrapper.__serve_multiplexed__ = True
        wrapper.__serve_multiplex_max__ = max_num_models_per_replica
        return wrapper

    return wrap(func) if func is not None else wrap


@ray_tpu.remote
class Replica:
    def __init__(self, callable_blob: bytes, init_args, init_kwargs,
                 max_ongoing: int = 8, user_config=None, deployment: str = ""):
        self._deployment = deployment
        # nested DeploymentHandles (model composition) arrive pre-resolved
        # inside init_args/kwargs
        target = cloudpickle.loads(callable_blob)
        if isinstance(target, type):
            self._callable = target(*init_args, **init_kwargs)
        elif init_args or init_kwargs:
            import functools

            self._callable = functools.partial(target, *init_args, **init_kwargs)
        else:
            self._callable = target
        self._gate = threading.Semaphore(max_ongoing)
        self._ongoing = 0
        self._ongoing_lock = threading.Lock()
        self._direct_lock = threading.Lock()
        # DRAINING: set once by prepare_drain(); new dispatches are rejected
        # with ReplicaDrainingError BEFORE entering the gate (so they never
        # count as accepted work), while in-flight requests — including open
        # streams and websocket sessions — run to completion
        self._draining = False
        self._replica_id_hex = ""
        if user_config is not None:
            self.reconfigure(user_config)

    def _replica_id(self) -> str:
        if not self._replica_id_hex:
            try:
                from ray_tpu._private.worker import get_runtime

                rid = getattr(get_runtime(), "_actor_id", None)
                self._replica_id_hex = rid.hex() if rid else ""
            except Exception:
                pass
        return self._replica_id_hex

    def _reject_if_draining(self):
        if self._draining:
            from ray_tpu.serve.exceptions import ReplicaDrainingError

            raise ReplicaDrainingError(self._deployment, self._replica_id())

    def prepare_drain(self) -> int:
        """Enter DRAINING: reject new dispatches, finish in-flight work.
        Returns the current ongoing count so the controller can log how
        much work the drain is waiting on. Idempotent. The flag flips under
        the ongoing lock: after this returns, every dispatch either already
        counts in ``num_ongoing`` or will be rejected — the controller's
        (draining AND idle) check is race-free."""
        with self._ongoing_lock:
            self._draining = True
            return self._ongoing

    def is_draining(self) -> bool:
        return self._draining

    def drain_status(self):
        """(draining, ongoing) read atomically — the drain loop's idle-kill
        predicate."""
        with self._ongoing_lock:
            return (self._draining, self._ongoing)

    def reconfigure(self, user_config) -> bool:
        """Apply a user_config without restarting the replica (parity: the
        deployment ``reconfigure`` contract, serve deployment docs /
        ``deployment_state.py`` lightweight-update path)."""
        fn = getattr(self._callable, "reconfigure", None)
        if callable(fn):
            fn(user_config)
        return True

    def _enter(self, model_id: str) -> float:
        """Admit one request; returns the replica-queue wait in ms (time
        spent gated behind max_ongoing — the serve span's queue stage)."""
        import time as _time

        with self._ongoing_lock:
            # checked under the SAME lock prepare_drain flips the flag
            # under: a request either counts in num_ongoing before the
            # drain begins, or is rejected — never a silent in-between the
            # drain loop's idle-kill could tear
            if self._draining:
                from ray_tpu.serve.exceptions import ReplicaDrainingError

                raise ReplicaDrainingError(self._deployment, self._replica_id_hex)
            self._ongoing += 1
            depth = self._ongoing
        self._record_depth(depth)
        t0 = _time.perf_counter()
        self._gate.acquire()
        queue_wait_ms = (_time.perf_counter() - t0) * 1e3
        _request_ctx.multiplexed_model_id = model_id
        return queue_wait_ms

    def _exit(self):
        self._gate.release()
        _request_ctx.multiplexed_model_id = ""
        with self._ongoing_lock:
            self._ongoing -= 1
            depth = self._ongoing
        self._record_depth(depth)

    def _record_depth(self, depth: int) -> None:
        try:
            _replica_metrics()["queue_depth"].set(
                float(depth), tags={"deployment": self._deployment}
            )
        except Exception:
            pass  # metrics never fail a request

    def _record_latency(self, method: str, seconds: float) -> None:
        try:
            tags = {"deployment": self._deployment, "method": method}
            m = _replica_metrics()
            m["latency"].observe(seconds * 1e3, tags=tags)
            m["requests"].inc(tags=tags)
        except Exception:
            pass
        try:
            # sliding-window sample with the request's trace id as exemplar
            # (aggregated per-deployment by the controller)
            from ray_tpu.util.tracing import current_trace_id

            win = getattr(self, "_latency_win", None)
            if win is None:
                from ray_tpu._private.telemetry import LatencyWindow
                from ray_tpu._private.worker import get_runtime

                window_s = float(
                    getattr(get_runtime().config, "latency_window_s", 60.0)
                )
                win = self._latency_win = LatencyWindow(window_s=window_s)
            win.observe(seconds * 1e3, current_trace_id())
        except Exception:
            pass

    def latency_samples(self, max_n: int = 512):
        """Raw in-window (ts, latency_ms, trace_id) samples — the
        controller folds every replica's into the per-deployment
        p50/p95/p99 series surfaced by serve.status()."""
        win = getattr(self, "_latency_win", None)
        if win is None:
            return []
        return win.raw()[-int(max_n):]

    def _record_ttft(self, ttft_ms: float) -> None:
        """Sliding-window TTFT sample (streaming responses only) — folded
        per-deployment by the controller, where it doubles as the
        TTFT-driven autoscaling signal (``target_ttft_ms``)."""
        try:
            from ray_tpu.util.tracing import current_trace_id

            win = getattr(self, "_ttft_win", None)
            if win is None:
                from ray_tpu._private.telemetry import LatencyWindow
                from ray_tpu._private.worker import get_runtime

                window_s = float(
                    getattr(get_runtime().config, "latency_window_s", 60.0)
                )
                win = self._ttft_win = LatencyWindow(window_s=window_s)
            win.observe(ttft_ms, current_trace_id())
        except Exception:
            pass

    def ttft_samples(self, max_n: int = 512):
        """Raw in-window (ts, ttft_ms, trace_id) stream-TTFT samples."""
        win = getattr(self, "_ttft_win", None)
        if win is None:
            return []
        return win.raw()[-int(max_n):]

    def _record_failure(self, method: str, error: BaseException) -> None:
        """Ship a request failure into the cluster event log (forensics
        plane) so ``list_cluster_events`` covers the serving path, not just
        core tasks. Rides the telemetry batch pipeline; never fails (or
        delays) the request path."""
        try:
            from ray_tpu._private.telemetry import record_cluster_event
            from ray_tpu._private.worker import get_runtime

            rt = get_runtime()
            replica_id = getattr(rt, "_actor_id", None)
            record_cluster_event(
                "REPLICA_REQUEST_FAILED",
                f"deployment {self._deployment or '?'}.{method} raised "
                f"{type(error).__name__}: {error}",
                severity="ERROR",
                source="SERVE",
                deployment=self._deployment,
                method=method,
                error_type=type(error).__name__,
                replica_id=replica_id.hex() if replica_id else None,
            )
        except Exception:
            pass

    def is_asgi(self) -> bool:
        """Whether this deployment mounts an ASGI app (serve.ingress)."""
        return getattr(self._callable, "__serve_asgi_app__", None) is not None

    def direct_address(self):
        """Start (once) and return the direct data-plane endpoint: proxies
        dial it and keep the connection for every subsequent request
        (parity: the proxy->replica gRPC channel, bypassing the control
        plane per request)."""
        with self._direct_lock:  # threaded actor: one listener, one port
            srv = getattr(self, "_direct_server", None)
            if srv is None:
                from ray_tpu._private.worker import get_runtime
                from ray_tpu.experimental.channel import _advertised_host
                from ray_tpu.serve._direct import DirectReplicaServer

                rt = get_runtime()
                key = rt.config.cluster_auth_key.encode()
                srv = self._direct_server = DirectReplicaServer(self, key)
                self._direct_host = _advertised_host(rt.config.cluster_host)
            return (self._direct_host, srv.port)

    def handle_request(self, method: str, args: List, kwargs: Dict, model_id: str = ""):
        import time as _time

        from ray_tpu._private.profiling import traced_section

        self._reject_if_draining()
        queue_wait_ms = self._enter(model_id)
        t0 = _time.perf_counter()
        try:
            with traced_section(
                f"serve:replica:{self._deployment}.{method}",
                {
                    "deployment": self._deployment,
                    "method": method,
                    "replica_id": self._replica_id(),
                    "queue_wait_ms": round(queue_wait_ms, 3),
                },
            ):
                if method == "__call__":
                    return self._callable(*args, **kwargs)
                return getattr(self._callable, method)(*args, **kwargs)
        except BaseException as e:
            self._record_failure(method, e)
            raise
        finally:
            self._record_latency(method, _time.perf_counter() - t0)
            self._exit()

    def handle_request_streaming(self, method: str, args: List, kwargs: Dict, model_id: str = ""):
        """Generator execution: items stream back as they are yielded
        (parity: streaming responses, _private/proxy_response_generator.py).
        The reserved ``__asgi__`` method drives the mounted ASGI app and
        streams its response events."""
        import time as _time

        from ray_tpu._private.profiling import traced_section

        self._reject_if_draining()
        queue_wait_ms = self._enter(model_id)
        t0 = _time.perf_counter()
        try:
            with traced_section(
                f"serve:replica:{self._deployment}.{method}",
                {
                    "deployment": self._deployment,
                    "method": method,
                    "replica_id": self._replica_id(),
                    "queue_wait_ms": round(queue_wait_ms, 3),
                },
            ) as span_extras:
                items = 0
                if method == "__asgi__":
                    from ray_tpu.serve._asgi import run_asgi_request

                    app = getattr(self._callable, "__serve_asgi_app__")
                    scope, body = args
                    gen = run_asgi_request(
                        app, scope, body, instance=self._callable
                    )
                else:
                    fn = (
                        self._callable
                        if method == "__call__"
                        else getattr(self._callable, method)
                    )
                    gen = fn(*args, **kwargs)
                for item in gen:
                    if items == 0:
                        # TTFT: request admitted -> first item yielded (the
                        # streaming span's headline stage)
                        ttft_ms = round((_time.perf_counter() - t0) * 1e3, 3)
                        span_extras["ttft_ms"] = ttft_ms
                        try:
                            _replica_metrics()["ttft"].observe(
                                ttft_ms,
                                tags={
                                    "deployment": self._deployment,
                                    "method": method,
                                },
                            )
                        except Exception:
                            pass
                        self._record_ttft(ttft_ms)
                    items += 1
                    yield item
                span_extras["stream_items"] = items
        except GeneratorExit:
            raise  # consumer stopped early: not a request failure
        except BaseException as e:
            self._record_failure(method, e)
            raise
        finally:
            # stream duration: entry to last yield (parity: serve counts a
            # streaming response until its generator finishes)
            self._record_latency(method, _time.perf_counter() - t0)
            self._exit()

    def handle_websocket(self, conn, scope) -> None:
        """One websocket session over a dedicated direct-plane connection
        (parity: the reference proxies websocket ASGI scopes through
        uvicorn, ``python/ray/serve/_private/proxy.py``). Counts toward
        ongoing-request depth for its whole lifetime, so autoscaling sees
        live sessions as load."""
        app = getattr(self._callable, "__serve_asgi_app__", None)
        if app is None:
            raise TypeError("deployment does not mount an ASGI app")
        self._reject_if_draining()
        from ray_tpu.serve._ws import run_asgi_websocket

        self._enter("")
        try:
            run_asgi_websocket(app, scope, conn, instance=self._callable)
        except BaseException as e:
            self._record_failure("__websocket__", e)
            raise
        finally:
            self._exit()

    def num_ongoing(self) -> int:
        """Queued + running requests (autoscaling metric)."""
        with self._ongoing_lock:
            return self._ongoing

    def multiplexed_model_ids(self) -> List[str]:
        out: List[str] = []
        caches = getattr(self._callable, "__serve_mux_caches__", None) or {}
        for cache in caches.values():
            out.extend(cache.model_ids())
        return out

    def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if callable(user_check):
            user_check()
        return True


# Expose the raw class under an importable name so cloudpickle serializes it
# by reference (the module attribute ``Replica`` is the ActorClass wrapper;
# without this the class pickles by value and drags module globals — e.g.
# the request-context threading.local — into the pickle).
_ReplicaImpl = Replica._cls
_ReplicaImpl.__qualname__ = "_ReplicaImpl"
