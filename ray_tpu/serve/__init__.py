"""Model serving library (Ray Serve equivalent).

Parity: ``python/ray/serve`` (SURVEY.md §2.4, §3.5) — control plane:
``ServeController`` actor reconciling deployments into replica actors
(``_private/controller.py:86``, ``deployment_state.py``); data plane:
``DeploymentHandle`` → power-of-two-choices replica routing
(``pow_2_scheduler.py:49``) → replica actors (threaded for concurrent
requests); HTTP proxy actor; dynamic batching (``batching.py``); model
composition via ``.bind()``.
"""

from ray_tpu.serve._asgi import ASGIApp, ingress
from ray_tpu.serve._replica import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.api import (
    delete,
    deployment,
    get_app_handle,
    get_deployment_handle,
    run,
    shutdown,
    status,
)
from ray_tpu.serve._grpc_proxy import grpc_predict, start_grpc_proxy
from ray_tpu.serve._proxy import start_node_proxies
from ray_tpu.serve.batching import batch
from ray_tpu.serve.schema import (
    build,
    deploy_config,
    deploy_config_file,
    dump_config,
)
from ray_tpu.serve.handle import (
    DeploymentHandle,
    DeploymentResponse,
    DeploymentResponseGenerator,
)
from ray_tpu.serve.exceptions import (
    DeploymentOverloadedError,
    ReplicaDiedError,
    ReplicaDrainingError,
    RequestTimeoutError,
    ServeError,
)

__all__ = [
    "deployment",
    "run",
    "shutdown",
    "delete",
    "status",
    "get_app_handle",
    "get_deployment_handle",
    "batch",
    "build",
    "deploy_config",
    "deploy_config_file",
    "dump_config",
    "grpc_predict",
    "start_grpc_proxy",
    "start_node_proxies",
    "ingress",
    "ASGIApp",
    "multiplexed",
    "get_multiplexed_model_id",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentResponseGenerator",
    "ServeError",
    "ReplicaDiedError",
    "ReplicaDrainingError",
    "DeploymentOverloadedError",
    "RequestTimeoutError",
    "llm",
]


def __getattr__(name):
    # the LLM plane imports jax via the model family; load it only when
    # asked for so plain serve users keep a jax-free import
    if name == "llm":
        import importlib

        mod = importlib.import_module("ray_tpu.serve.llm")
        globals()["llm"] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

from ray_tpu._private import usage as _usage

_usage.record_library_usage("serve")
del _usage
