"""gRPC ingress proxy actor.

Parity: the reference's gRPC proxy (``python/ray/serve/_private/proxy.py``
gRPCProxy + ``serve/grpc_util.py``): a second ingress protocol next to HTTP.
The service is defined with a generic handler (no protoc step): one unary
method ``/ray_tpu.serve.ServeAPI/Predict`` whose request/response are pickled
payloads, with the target application selected by the ``application``
metadata key (the reference routes gRPC by application metadata the same
way).
"""

from __future__ import annotations

import hmac
import hashlib
import pickle
from typing import Dict, Optional

import ray_tpu

_GRPC_PROXY_NAME = "SERVE_GRPC_PROXY"
SERVICE_METHOD = "/ray_tpu.serve.ServeAPI/Predict"
_SIG_LEN = 32


def _cluster_key() -> bytes:
    from ray_tpu._private.worker import get_runtime

    return get_runtime().config.cluster_auth_key.encode()


def _sign(key: bytes, blob: bytes) -> bytes:
    return hmac.new(key, blob, hashlib.sha256).digest()


def _frame(key: bytes, obj) -> bytes:
    blob = pickle.dumps(obj)
    return _sign(key, blob) + blob


def _unframe(key: bytes, framed: bytes):
    """Verify the HMAC prefix before unpickling — pickles execute code, so
    an unauthenticated local process must never reach ``pickle.loads`` (the
    same reason every other socket in this codebase does challenge auth)."""
    sig, blob = framed[:_SIG_LEN], framed[_SIG_LEN:]
    if len(sig) != _SIG_LEN or not hmac.compare_digest(_sign(key, blob), sig):
        raise PermissionError("bad or missing cluster auth signature")
    return pickle.loads(blob)


@ray_tpu.remote(max_concurrency=16)
class GRPCProxy:
    def __init__(self, port: int = 0):
        import grpc
        from concurrent import futures

        self._handles: Dict[str, object] = {}
        self._key = _cluster_key()
        proxy = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method != SERVICE_METHOD:
                    return None
                meta = dict(handler_call_details.invocation_metadata)
                app = meta.get("application", "default")

                def unary(request_bytes, context):
                    try:
                        payload = _unframe(proxy._key, request_bytes)
                    except PermissionError as e:
                        context.abort(
                            grpc.StatusCode.UNAUTHENTICATED, str(e)
                        )
                    try:
                        result = proxy._call(app, payload)
                        return _frame(proxy._key, {"result": result})
                    except Exception as e:  # noqa: BLE001
                        return _frame(proxy._key, {"error": repr(e)})

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=None,  # raw bytes in/out
                    response_serializer=None,
                )

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=16))
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")
        self._server.start()

    def _call(self, app: str, payload):
        from ray_tpu import serve

        handle = self._handles.get(app)
        if handle is None:
            handle = serve.get_app_handle(app)
            self._handles[app] = handle
        from ray_tpu import exceptions as exc

        try:
            return handle.remote(payload).result(timeout_s=60)
        except (exc.ActorDiedError, exc.GetTimeoutError):
            # replica set changed (redeploy/autoscale): refresh and retry
            # once. Application exceptions propagate unretried — replaying a
            # failed request would double non-idempotent side effects.
            self._handles.pop(app, None)
            handle = serve.get_app_handle(app)
            self._handles[app] = handle
            return handle.remote(payload).result(timeout_s=60)

    def invalidate(self, app: str):
        self._handles.pop(app, None)
        return True

    def get_port(self) -> int:
        return self.port

    def check_health(self) -> bool:
        return True


def start_grpc_proxy(port: int = 0):
    """Start (or fetch) the cluster's gRPC ingress; returns its port."""
    try:
        proxy = ray_tpu.get_actor(_GRPC_PROXY_NAME)
    except ValueError:
        try:
            proxy = GRPCProxy.options(
                name=_GRPC_PROXY_NAME, num_cpus=0, max_concurrency=32
            ).remote(port)
        except ValueError:  # racing creator won
            proxy = ray_tpu.get_actor(_GRPC_PROXY_NAME)
    return ray_tpu.get(proxy.get_port.remote(), timeout=60)


def grpc_predict(address: str, payload, *, application: str = "default",
                 timeout_s: float = 60.0):
    """Client helper: call the Serve gRPC ingress (HMAC-framed pickled
    unary; the caller must share the cluster auth key)."""
    import grpc

    key = _cluster_key()
    channel = grpc.insecure_channel(address)
    try:
        fn = channel.unary_unary(SERVICE_METHOD)
        reply = _unframe(
            key,
            fn(
                _frame(key, payload),
                metadata=(("application", application),),
                timeout=timeout_s,
            ),
        )
    finally:
        channel.close()
    if "error" in reply:
        raise RuntimeError(f"serve grpc call failed: {reply['error']}")
    return reply["result"]
