"""WebSocket support for the Serve ingress.

Parity: the reference proxies any ASGI scope type — including websockets —
by embedding uvicorn (``python/ray/serve/_private/proxy.py``); Serve apps
receive ``websocket`` scopes like any Starlette/FastAPI app. Here the
hand-rolled HTTP front end performs the RFC 6455 upgrade itself and relays
frames over a DEDICATED proxy→replica connection (dialed per session from
the replica's direct data-plane listener, ``serve/_direct.py``):

    client ⇄ proxy              ws frames (this codec)
    proxy  ⇄ replica            ("msg", asgi_event) upstream,
                                ("evt", asgi_event) downstream
    replica ⇄ user ASGI app     standard websocket.* events

The app sees the standard ASGI websocket lifecycle: ``websocket.connect`` →
``websocket.accept`` (or ``websocket.close`` → HTTP 403, per spec) →
``websocket.receive``/``websocket.send`` → ``websocket.disconnect``.

Websocket sessions require the direct data plane (the head-relayed handle
path is unidirectional); with no live replica channel the proxy answers 503.
"""

from __future__ import annotations

import base64
import hashlib
import os
import queue
import struct
import threading
from typing import Optional, Tuple

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
MAX_FRAME = 64 * 1024 * 1024

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def accept_key(client_key: str) -> str:
    """Sec-WebSocket-Accept for a client's Sec-WebSocket-Key (RFC 6455 §4.2.2)."""
    digest = hashlib.sha1((client_key + _GUID).encode("latin1")).digest()
    return base64.b64encode(digest).decode()


def _xor_mask(data: bytes, mask: bytes) -> bytes:
    n = len(data)
    if n == 0:
        return b""
    m = (mask * (n // 4 + 1))[:n]
    return (int.from_bytes(data, "little") ^ int.from_bytes(m, "little")).to_bytes(
        n, "little"
    )


def encode_frame(opcode: int, payload: bytes, fin: bool = True, mask: bool = False) -> bytes:
    """One frame. Servers send unmasked; clients must mask (RFC 6455 §5.3)."""
    b0 = (0x80 if fin else 0) | opcode
    mbit = 0x80 if mask else 0
    n = len(payload)
    if n < 126:
        head = struct.pack("!BB", b0, mbit | n)
    elif n < 1 << 16:
        head = struct.pack("!BBH", b0, mbit | 126, n)
    else:
        head = struct.pack("!BBQ", b0, mbit | 127, n)
    if mask:
        mk = os.urandom(4)
        return head + mk + _xor_mask(payload, mk)
    return head + payload


def encode_close(code: int = 1000, reason: str = "", mask: bool = False) -> bytes:
    # close payload caps at 125 bytes (2 for the code); the reason must stay
    # valid UTF-8 after truncation (RFC 6455 §5.5.1), so cut on a codepoint
    # boundary, never mid-sequence
    raw = reason.encode("utf-8")
    if len(raw) > 123:
        raw = raw[:123].decode("utf-8", errors="ignore").encode("utf-8")
    payload = struct.pack("!H", code) + raw
    return encode_frame(OP_CLOSE, payload, mask=mask)


def parse_close(payload: bytes) -> Tuple[int, str]:
    if len(payload) >= 2:
        code = struct.unpack("!H", payload[:2])[0]
        try:
            reason = payload[2:].decode("utf-8")
        except UnicodeDecodeError:
            reason = ""
        return code, reason
    return 1005, ""


async def read_frame(reader) -> Tuple[bool, int, bytes]:
    """Read one frame from an ``asyncio.StreamReader`` → (fin, opcode, payload),
    unmasking when the peer masked (clients always do)."""
    hdr = await reader.readexactly(2)
    b0, b1 = hdr[0], hdr[1]
    fin = bool(b0 & 0x80)
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    length = b1 & 0x7F
    if length == 126:
        length = struct.unpack("!H", await reader.readexactly(2))[0]
    elif length == 127:
        length = struct.unpack("!Q", await reader.readexactly(8))[0]
    if length > MAX_FRAME:
        raise ValueError(f"websocket frame exceeds {MAX_FRAME} bytes")
    mask = await reader.readexactly(4) if masked else None
    payload = await reader.readexactly(length) if length else b""
    if mask:
        payload = _xor_mask(payload, mask)
    return fin, opcode, payload


class MessageReader:
    """Reassembles fragmented messages across calls. Control frames may be
    injected INSIDE a fragmented message (RFC 6455 §5.4): they surface
    immediately while the partial data message stays buffered here, so the
    continuation frames that follow still have their message in progress."""

    def __init__(self, reader):
        self._reader = reader
        self._opcode: Optional[int] = None
        self._parts: list = []
        self._total = 0

    async def next(self) -> Tuple[int, bytes]:
        while True:
            fin, op, payload = await read_frame(self._reader)
            if op in (OP_CLOSE, OP_PING, OP_PONG):
                return op, payload
            if op != OP_CONT:
                self._opcode = op
                self._parts = [payload]
                self._total = len(payload)
            else:
                if self._opcode is None:
                    raise ValueError(
                        "continuation frame with no message in progress"
                    )
                self._parts.append(payload)
                self._total += len(payload)
            if self._total > MAX_FRAME:
                raise ValueError(
                    f"websocket message exceeds {MAX_FRAME} bytes"
                )
            if fin:
                op, data = self._opcode, b"".join(self._parts)
                self._opcode, self._parts, self._total = None, [], 0
                return op, data


async def read_message(reader) -> Tuple[int, bytes]:
    """One-shot form of MessageReader for callers without interleaved
    control-frame concerns (a fragmented message must complete within the
    call). Prefer MessageReader for session loops."""
    return await MessageReader(reader).next()


# ---------------------------------------------------------------------------
# Replica side: drive the user ASGI app over a dedicated proxy connection.
# ---------------------------------------------------------------------------


def run_asgi_websocket(asgi_app, scope, conn, instance=None) -> None:
    """Execute one websocket session against ``asgi_app`` on the replica.

    ``conn`` is the dedicated proxy connection (multiprocessing.connection):
    upstream ASGI events arrive as ``("msg", event)`` records (fed by a
    reader thread into the app's ``receive``), downstream ``send`` events
    leave as ``("evt", event)``; ``("end", None)`` / ``("err", blob)``
    terminate the session. Runs on the direct server's per-connection
    thread; the app gets its own event loop.
    """
    import asyncio
    import pickle

    import cloudpickle

    scope = dict(scope)
    scope["type"] = "websocket"
    scope["headers"] = [(bytes(k), bytes(v)) for k, v in scope.get("headers", [])]
    scope.setdefault("asgi", {"version": "3.0", "spec_version": "2.3"})
    ext = dict(scope.get("extensions") or {})
    ext["serve_replica"] = instance
    scope["extensions"] = ext

    upstream: "queue.Queue" = queue.Queue(maxsize=256)
    send_lock = threading.Lock()
    closed = threading.Event()

    def put_upstream(event) -> bool:
        """Interruptible bounded put: never wedges past session close, so
        the serving thread (and its ongoing-request slot) always frees."""
        while not closed.is_set():
            try:
                upstream.put(event, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def reader():
        try:
            while not closed.is_set():
                kind, event = conn.recv()
                if kind == "msg":
                    if not put_upstream(event):
                        return
                    if event.get("type") == "websocket.disconnect":
                        return
        except (EOFError, OSError):
            put_upstream({"type": "websocket.disconnect", "code": 1006})

    rt = threading.Thread(target=reader, daemon=True, name="serve-ws-up")
    rt.start()

    connected = False
    disconnected: list = [False, 1006]

    async def receive():
        nonlocal connected
        if not connected:
            connected = True
            return {"type": "websocket.connect"}
        if disconnected[0]:
            # sticky: an app polling receive() after the disconnect must
            # not block forever on the drained queue
            return {"type": "websocket.disconnect", "code": disconnected[1]}
        loop = asyncio.get_running_loop()

        def _get():
            # poll, don't park: an abandoned receive() (wait_for timeout,
            # cancelled race) leaves this executor thread behind — it must
            # notice session close and exit, or loop shutdown joins it for
            # minutes and the serving thread + ongoing-request slot wedge
            while True:
                try:
                    return upstream.get(timeout=0.5)
                except queue.Empty:
                    if closed.is_set():
                        return {"type": "websocket.disconnect", "code": 1006}

        ev = await loop.run_in_executor(None, _get)
        if ev.get("type") == "websocket.disconnect":
            disconnected[0] = True
            disconnected[1] = ev.get("code", 1006)
        return ev

    async def send(event):
        if closed.is_set():
            raise RuntimeError("websocket session closed")
        with send_lock:
            conn.send(("evt", event))

    async def _session():
        try:
            await asgi_app(scope, receive, send)
        finally:
            # set BEFORE the loop shuts down its default executor: any
            # executor thread still polling in receive()'s _get must see
            # this and exit, or asyncio.run would join it for minutes
            closed.set()

    try:
        asyncio.run(_session())
        with send_lock:
            conn.send(("end", None))
    except (EOFError, OSError, BrokenPipeError):
        pass  # proxy/client went away mid-session
    except BaseException as e:  # noqa: BLE001
        try:
            blob = cloudpickle.dumps(e)
        except Exception:
            blob = pickle.dumps(RuntimeError(str(e)))
        try:
            with send_lock:
                conn.send(("err", blob))
        except (OSError, BrokenPipeError):
            pass
    finally:
        closed.set()
        # unblock a pending upstream.get if the app leaked one; never block
        # here — a full queue already has a wakeup for the getter
        try:
            upstream.put_nowait({"type": "websocket.disconnect", "code": 1006})
        except queue.Full:
            pass


# ---------------------------------------------------------------------------
# Minimal synchronous client (tests / simple consumers).
# ---------------------------------------------------------------------------


class WSClient:
    """Blocking RFC 6455 client over a raw socket — enough for tests and
    simple tooling (text/binary/ping/close; no extensions/compression)."""

    def __init__(self, host: str, port: int, path: str = "/",
                 subprotocols=(), timeout: float = 30.0):
        import socket as _socket

        self._sock = _socket.create_connection((host, port), timeout=timeout)
        self._buf = b""
        key = base64.b64encode(os.urandom(16)).decode()
        lines = [
            f"GET {path} HTTP/1.1",
            f"Host: {host}:{port}",
            "Upgrade: websocket",
            "Connection: Upgrade",
            f"Sec-WebSocket-Key: {key}",
            "Sec-WebSocket-Version: 13",
        ]
        if subprotocols:
            lines.append("Sec-WebSocket-Protocol: " + ", ".join(subprotocols))
        self._sock.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())
        status, headers = self._read_http_response()
        self.status = status
        self.response_headers = headers
        if status != 101:
            self._sock.close()
            raise ConnectionError(f"websocket upgrade refused: HTTP {status}")
        expect = accept_key(key)
        if headers.get("sec-websocket-accept") != expect:
            self._sock.close()
            raise ConnectionError("bad Sec-WebSocket-Accept")
        self.subprotocol = headers.get("sec-websocket-protocol")

    def _read_http_response(self):
        while b"\r\n\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed during upgrade")
            self._buf += chunk
        head, self._buf = self._buf.split(b"\r\n\r\n", 1)
        lines = head.decode("latin1").split("\r\n")
        status = int(lines[0].split(" ", 2)[1])
        headers = {}
        for ln in lines[1:]:
            k, _, v = ln.partition(":")
            headers[k.strip().lower()] = v.strip()
        return status, headers

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _read_frame(self):
        hdr = self._read_exact(2)
        fin = bool(hdr[0] & 0x80)
        opcode = hdr[0] & 0x0F
        masked = bool(hdr[1] & 0x80)
        length = hdr[1] & 0x7F
        if length == 126:
            length = struct.unpack("!H", self._read_exact(2))[0]
        elif length == 127:
            length = struct.unpack("!Q", self._read_exact(8))[0]
        mask = self._read_exact(4) if masked else None
        payload = self._read_exact(length) if length else b""
        if mask:
            payload = _xor_mask(payload, mask)
        return fin, opcode, payload

    def send_text(self, text: str) -> None:
        self._sock.sendall(encode_frame(OP_TEXT, text.encode("utf-8"), mask=True))

    def send_bytes(self, data: bytes) -> None:
        self._sock.sendall(encode_frame(OP_BINARY, data, mask=True))

    def ping(self, payload: bytes = b"") -> None:
        self._sock.sendall(encode_frame(OP_PING, payload, mask=True))

    def recv(self):
        """Next message: str (text), bytes (binary), or ("close", code, reason).
        Pongs answer pings transparently; solicited pongs surface as
        ("pong", payload)."""
        opcode = None
        parts = []
        while True:
            fin, op, payload = self._read_frame()
            if op == OP_CLOSE:
                code, reason = parse_close(payload)
                try:
                    self._sock.sendall(encode_close(code, mask=True))
                except OSError:
                    pass
                return ("close", code, reason)
            if op == OP_PING:
                self._sock.sendall(encode_frame(OP_PONG, payload, mask=True))
                continue
            if op == OP_PONG:
                return ("pong", payload)
            if op != OP_CONT:
                opcode = op
                parts = [payload]
            else:
                parts.append(payload)
            if fin:
                data = b"".join(parts)
                return data.decode("utf-8") if opcode == OP_TEXT else data

    def close(self, code: int = 1000, reason: str = "") -> None:
        try:
            self._sock.sendall(encode_close(code, reason, mask=True))
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
