"""Typed serve data-plane errors (resilience plane).

Parity: ``python/ray/serve/exceptions.py`` (``RayServeException``,
``BackPressureError``, ``RequestCancelledError``) plus the failover
semantics of the replica scheduler: a request that provably never started
executing is transparently retried on another replica, while torn work —
a call or stream the dead replica had already begun — surfaces as a typed
:class:`ReplicaDiedError` carrying provenance so callers can decide
whether re-issuing is safe for THEIR semantics.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.exceptions import GetTimeoutError, RayTpuError


class ServeError(RayTpuError):
    """Base class for serve data-plane errors."""


class ReplicaDrainingError(ServeError):
    """The replica rejected the dispatch because it is DRAINING (graceful
    shutdown in progress). The request never entered execution, so it is
    ALWAYS safe to retry on another replica; handles and the direct proxy
    channel do so transparently."""

    def __init__(self, deployment: str = "", replica_id: str = ""):
        self.deployment = deployment
        self.replica_id = replica_id
        super().__init__(
            f"replica {replica_id[:12] or '?'} of deployment "
            f"'{deployment or '?'}' is draining"
        )

    def __reduce__(self):
        return (ReplicaDrainingError, (self.deployment, self.replica_id))


class ReplicaDiedError(ServeError):
    """The replica died under this request and the work cannot be proven
    un-started (unary call already executing, or a stream that had begun
    yielding). Carries provenance: which deployment/replica, which method,
    and whether execution had observably started (``started=True``) or the
    runtime could not tell (``started=None``)."""

    def __init__(
        self,
        deployment: str = "",
        app: str = "",
        method: str = "",
        replica_id: str = "",
        started: Optional[bool] = None,
        reason: str = "replica died",
    ):
        self.deployment = deployment
        self.app = app
        self.method = method
        self.replica_id = replica_id
        self.started = started
        self.reason = reason
        state = {True: "started", False: "unstarted", None: "unknown-progress"}[
            started if started in (True, False) else None
        ]
        super().__init__(
            f"replica {replica_id[:12] or '?'} of '{app or '?'}/"
            f"{deployment or '?'}' died under {state} request "
            f"{method or '?'}(): {reason}"
        )

    def __reduce__(self):
        return (
            ReplicaDiedError,
            (
                self.deployment,
                self.app,
                self.method,
                self.replica_id,
                self.started,
                self.reason,
            ),
        )


class DeploymentOverloadedError(ServeError):
    """Admission control shed this request: the deployment's queue bound
    (``max_ongoing_requests x replicas x shed_queue_factor``) is exceeded.
    Fast-fail instead of queueing into a guaranteed timeout; retry after
    ``retry_after_s`` (the HTTP proxy maps this to 503 + ``Retry-After``)."""

    def __init__(
        self,
        deployment: str = "",
        retry_after_s: float = 1.0,
        load: int = 0,
        capacity: int = 0,
    ):
        self.deployment = deployment
        self.retry_after_s = retry_after_s
        self.load = load
        self.capacity = capacity
        super().__init__(
            f"deployment '{deployment or '?'}' is overloaded "
            f"(load {load} >= capacity {capacity}); retry in {retry_after_s:g}s"
        )

    def __reduce__(self):
        return (
            DeploymentOverloadedError,
            (self.deployment, self.retry_after_s, self.load, self.capacity),
        )


class RequestTimeoutError(ServeError, GetTimeoutError):
    """A serve request (or one item of a streaming response) exceeded its
    timeout. Subclasses :class:`GetTimeoutError` so existing callers that
    catch the generic get-timeout keep working."""

    def __init__(self, deployment: str = "", method: str = "", timeout_s: float = 0.0):
        self.deployment = deployment
        self.method = method
        self.timeout_s = timeout_s
        super().__init__(
            f"request {method or '?'}() to deployment '{deployment or '?'}' "
            f"timed out after {timeout_s:g}s"
        )

    def __reduce__(self):
        return (RequestTimeoutError, (self.deployment, self.method, self.timeout_s))


class ControllerUnavailableError(ServeError):
    """The serve controller is (temporarily) unreachable. Data-plane
    handles keep routing to their cached replica set meanwhile."""
