"""ASGI app mounting for Serve deployments.

Parity: ``serve.ingress(app)`` (``python/ray/serve/api.py``) — the reference
mounts FastAPI/Starlette apps on deployments and drives them from uvicorn
inside the proxy/replica. Here the proxy forwards the raw HTTP exchange
(scope + body) to the replica, which drives the ASGI protocol itself: the
app's ``send`` events stream back through the handle's streaming path, so
chunked/streaming responses flow end-to-end without buffering.

Any ASGI-3 callable works — FastAPI/Starlette if installed, or a plain

    async def app(scope, receive, send): ...
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Iterator, List, Tuple


def ingress(asgi_app):
    """Class decorator mounting an ASGI app on a deployment.

    The decorated class's replicas answer HTTP through the app; other
    methods remain callable through the handle as usual. If the app wants
    the replica instance, it can read ``scope["extensions"]["serve_replica"]``.
    """

    def decorator(cls):
        cls.__serve_asgi_app__ = staticmethod(asgi_app)
        return cls

    return decorator


class ASGIApp:
    """Bare-app deployment target: ``serve.run(serve.deployment(ASGIApp).bind(app))``
    — or use :func:`ingress` on your own class."""

    def __init__(self, asgi_app):
        self.__serve_asgi_app__ = asgi_app


def run_asgi_request(
    asgi_app,
    scope: Dict[str, Any],
    body: bytes,
    instance: Any = None,
) -> Iterator[Tuple]:
    """Drive one request through an ASGI app, yielding response events.

    Yields ``("start", status, headers)`` once, then ``("body", bytes,
    more_body)`` until the app completes. The app runs on a private event
    loop in a helper thread so events stream as they are sent (a
    StreamingResponse's chunks arrive incrementally, not buffered).
    """
    import asyncio

    # bounded: a slow consumer (ultimately the HTTP client) must
    # backpressure the app's send, not buffer its stream in replica memory
    q: "queue.Queue" = queue.Queue(maxsize=64)
    abandoned = threading.Event()
    # rebuild bytes-pair headers (they cross the wire as lists)
    scope = dict(scope)
    scope["headers"] = [
        (bytes(k), bytes(v)) for k, v in scope.get("headers", [])
    ]
    scope.setdefault("type", "http")
    scope.setdefault("asgi", {"version": "3.0", "spec_version": "2.3"})
    ext = dict(scope.get("extensions") or {})
    ext["serve_replica"] = instance
    scope["extensions"] = ext

    def runner():
        consumed = False

        async def receive():
            nonlocal consumed
            if not consumed:
                consumed = True
                return {"type": "http.request", "body": body, "more_body": False}
            return {"type": "http.disconnect"}

        def put(item) -> bool:
            while not abandoned.is_set():
                try:
                    q.put(item, timeout=1.0)
                    return True
                except queue.Full:
                    continue
            return False

        async def send(event):
            if not put(event):
                raise RuntimeError("ASGI response consumer went away")

        try:
            asyncio.run(asgi_app(scope, receive, send))
            put(None)
        except BaseException as e:  # noqa: BLE001
            put(e)

    t = threading.Thread(target=runner, daemon=True, name="asgi-request")
    t.start()

    started = False
    try:
        while True:
            event = q.get()
            if event is None:
                if not started:
                    raise RuntimeError("ASGI app completed without a response")
                return
            if isinstance(event, BaseException):
                # before start: a clean 500 for the proxy to render; after
                # start: propagate so the proxy TRUNCATES the chunked stream
                # (a crash must never masquerade as a complete 200)
                raise event
            kind = event.get("type")
            if kind == "http.response.start":
                started = True
                headers: List[Tuple[bytes, bytes]] = [
                    (bytes(k), bytes(v)) for k, v in event.get("headers", [])
                ]
                yield ("start", int(event.get("status", 200)), headers)
            elif kind == "http.response.body":
                yield (
                    "body",
                    bytes(event.get("body", b"")),
                    bool(event.get("more_body", False)),
                )
                if not event.get("more_body", False):
                    return
    finally:
        # consumer gone (client disconnect) or complete: unblock the app
        # thread's bounded put so it can exit instead of leaking
        abandoned.set()
