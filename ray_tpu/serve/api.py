"""Serve public API: deployments, applications, run/shutdown.

Parity: ``python/ray/serve/api.py`` (``serve.run`` ``:535``) +
``ServeController`` (``_private/controller.py:86``): a detached named
controller actor owns the deployment table and reconciles replica actors
(restart on death); ``.bind()`` builds composition graphs whose nested nodes
become DeploymentHandles (``deployment_graph_build.py``).

Resilience plane (this module is the control-plane half; ``handle.py`` /
``_direct.py`` are the data plane):

* **graceful drain** — every kill path (redeploy, autoscale-down,
  ``delete``, ``shutdown``) marks replicas DRAINING (new dispatches
  rejected, in-flight work incl. open streams/websockets finishes) and only
  kills them once idle or past the deployment's
  ``graceful_shutdown_timeout_s`` (parity: ``deployment_state.py``'s
  graceful-stop + proxy draining);
* **health states** — the reconcile loop drives per-deployment
  HEALTHY / DEGRADED / UNHEALTHY off parallel health probes, emitting
  DEPLOYMENT_UNHEALTHY / REPLICA_DIED cluster events;
* **controller fault tolerance** — app specs, routes, and replica ids
  persist to the GCS KV on every mutation; the controller is a detached,
  infinitely-restartable actor whose fresh incarnation restores the tables
  and RE-ADOPTS still-alive replicas instead of cold-starting the fleet
  (parity: serve controller state in the GCS, ``kv_store.py``).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.serve._replica import Replica
from ray_tpu.serve.handle import DeploymentHandle

logger = logging.getLogger(__name__)

_CONTROLLER_NAME = "SERVE_CONTROLLER"
_KV_NS = "serve"
_KV_APPS = b"apps"
_KV_ROUTES = b"routes"
_KV_REPLICAS = b"replicas"
_KV_DRAINING = b"draining"

# controller-side telemetry; lazy singletons (records are local dict
# updates batched by the telemetry plane)
_metrics: dict = {}


def _controller_metrics() -> dict:
    if not _metrics:
        from ray_tpu.util.metrics import Counter

        _metrics["drained"] = Counter(
            "ray_tpu_serve_drained_total",
            "replicas gracefully drained before kill",
            tag_keys=("deployment",),
        )
        _metrics["deaths"] = Counter(
            "ray_tpu_serve_replica_deaths_total",
            "serving replicas that died outside a drain",
            tag_keys=("deployment",),
        )
    return _metrics


def _inc(name: str, deployment: str) -> None:
    try:
        _controller_metrics()[name].inc(tags={"deployment": deployment})
    except Exception:
        pass


def _event(type: str, message: str, severity: str = "INFO", **extra) -> None:
    try:
        from ray_tpu._private.telemetry import record_cluster_event

        record_cluster_event(type, message, severity=severity, source="SERVE", **extra)
    except Exception:
        pass


@dataclass
class Application:
    """A bound deployment graph node."""

    deployment: "Deployment"
    args: tuple
    kwargs: dict


class Deployment:
    """One deployment's declaration.

    Resilience knobs (see DESIGN_MAP "Serve resilience"):

    * ``graceful_shutdown_timeout_s`` — on redeploy / autoscale-down /
      delete / shutdown a replica drains (rejects new dispatches, finishes
      in-flight work including open streams and websocket sessions) for up
      to this long before being killed. Default 20s.
    * ``request_retries`` — failover budget per request: calls the
      scheduler proves never started executing on a dead/draining replica
      are transparently retried on another replica up to this many times
      (torn work instead raises a typed ``ReplicaDiedError``). Default 3.
    * ``request_timeout_s`` — per-request budget the HTTP proxy applies to
      dispatches for this deployment (504 on expiry instead of an unbounded
      hang). Default 120s.
    * ``shed_queue_factor`` / ``shed_retry_after_s`` — admission control:
      once queued work exceeds ``replicas x max_ongoing_requests x
      shed_queue_factor`` new requests are shed with
      ``DeploymentOverloadedError`` (HTTP: fast 503 + ``Retry-After:
      shed_retry_after_s``) instead of queueing into a guaranteed timeout;
      a half-open probe per window re-tests freed capacity. For autoscaled
      deployments capacity is computed against ``max_replicas`` (queued
      work is the scale-up signal — shedding it would starve the
      autoscaler). Default factor 6.0.
    * ``health_check_period_s`` — reconcile-loop probe period for this
      deployment (replica health + queue-depth sampling).
    """

    def __init__(self, target, *, name=None, num_replicas=1, max_ongoing_requests=8,
                 ray_actor_options=None, health_check_period_s=5.0,
                 autoscaling_config=None, user_config=None,
                 graceful_shutdown_timeout_s=20.0, request_timeout_s=120.0,
                 request_retries=3, shed_queue_factor=6.0,
                 shed_retry_after_s=1.0):
        self._target = target
        self.name = name or getattr(target, "__name__", "deployment")
        self.num_replicas = num_replicas
        self.max_ongoing_requests = max_ongoing_requests
        self.ray_actor_options = ray_actor_options or {}
        self.health_check_period_s = health_check_period_s
        # {"min_replicas", "max_replicas", "target_ongoing_requests"}
        # (parity: serve autoscaling_policy.py / autoscaling_state.py)
        self.autoscaling_config = dict(autoscaling_config or {}) or None
        # opaque config delivered to the callable's reconfigure() — updating
        # ONLY this on redeploy is a lightweight update (no replica restart)
        self.user_config = user_config
        self.graceful_shutdown_timeout_s = graceful_shutdown_timeout_s
        self.request_timeout_s = request_timeout_s
        self.request_retries = request_retries
        self.shed_queue_factor = shed_queue_factor
        self.shed_retry_after_s = shed_retry_after_s

    _OPTION_KEYS = (
        "name",
        "num_replicas",
        "max_ongoing_requests",
        "ray_actor_options",
        "health_check_period_s",
        "autoscaling_config",
        "user_config",
        "graceful_shutdown_timeout_s",
        "request_timeout_s",
        "request_retries",
        "shed_queue_factor",
        "shed_retry_after_s",
    )

    def options(self, **updates) -> "Deployment":
        kwargs = {k: updates.get(k, getattr(self, k)) for k in self._OPTION_KEYS}
        return Deployment(self._target, **kwargs)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def spec(self) -> dict:
        num = self.num_replicas
        if self.autoscaling_config:
            num = int(self.autoscaling_config.get("min_replicas", 1)) or 1
        return {
            "name": self.name,
            "callable_blob": cloudpickle.dumps(self._target),
            "num_replicas": num,
            "max_ongoing_requests": self.max_ongoing_requests,
            "ray_actor_options": self.ray_actor_options,
            "autoscaling_config": self.autoscaling_config,
            "user_config": self.user_config,
            "health_check_period_s": self.health_check_period_s,
            "graceful_shutdown_timeout_s": self.graceful_shutdown_timeout_s,
            "request_timeout_s": self.request_timeout_s,
            "request_retries": self.request_retries,
            "shed_queue_factor": self.shed_queue_factor,
            "shed_retry_after_s": self.shed_retry_after_s,
        }


def deployment(target=None, **options):
    """``@serve.deployment`` decorator (parity: ``api.py``).

    Works bare (``@serve.deployment``), parametrised
    (``@serve.deployment(num_replicas=2)``), and as a direct call with
    both (``serve.deployment(MyClass, num_replicas=2)``) — options must
    never be silently dropped in the direct-call form."""
    if target is not None and callable(target):
        return Deployment(target, **options)

    def wrap(t):
        return Deployment(t, **options)

    return wrap


def _handle_config(spec: dict) -> dict:
    """The per-deployment knobs a DeploymentHandle needs (shipped through
    get_handle_info so live handles track redeploys)."""
    autoscaling = spec.get("autoscaling_config") or {}
    return {
        "max_ongoing": spec.get("max_ongoing_requests", 8),
        "shed_queue_factor": spec.get("shed_queue_factor", 6.0),
        "shed_retry_after_s": spec.get("shed_retry_after_s", 1.0),
        "request_timeout_s": spec.get("request_timeout_s", 120.0),
        "request_retries": spec.get("request_retries", 3),
        "graceful_shutdown_timeout_s": spec.get("graceful_shutdown_timeout_s", 20.0),
        "max_replicas": autoscaling.get("max_replicas"),
    }


@ray_tpu.remote(max_concurrency=8)
class ServeController:
    """Control plane: deployment table + replica reconciliation.

    Every mutation of ``apps``/``routes``/replica sets persists to the GCS
    KV (ns ``serve``); ``__init__`` restores from it and re-adopts replicas
    that are still alive, so a controller death (or a head restart replaying
    the detached-actor snapshot) never cold-starts the fleet.
    """

    RECONCILE_TICK_S = 0.25
    DRAIN_TICK_S = 0.2
    PROBE_BUDGET_S = 10.0

    def __init__(self):
        import threading

        # app -> deployment name -> {spec, replicas: [handles], ...}
        self.apps: Dict[str, Dict[str, dict]] = {}
        # route_prefix -> app name (pushed to every proxy, incl. per-node)
        self.routes: Dict[str, str] = {}
        self._stop = False
        # guards self.apps mutations against the reconciler thread (this actor
        # is threaded, so handlers run concurrently)
        self._lock = threading.Lock()
        # replicas draining toward a kill: {replica, rid, deadline, app,
        # deployment}; reaped by the drain loop once idle or past deadline
        self._draining: List[dict] = []
        self._drain_lock = threading.Lock()
        self._restore_state()
        self._reconciler = threading.Thread(target=self._reconcile_loop, daemon=True)
        self._reconciler.start()
        self._drainer = threading.Thread(target=self._drain_loop, daemon=True)
        self._drainer.start()

    # -- GCS KV persistence ------------------------------------------------

    @staticmethod
    def _kv_call(op: str, *args):
        from ray_tpu._private.worker import get_runtime

        rt = get_runtime()
        if hasattr(rt, "scheduler_rpc"):
            return rt.scheduler_rpc(op, (_KV_NS,) + args)
        return rt.rpc(op, _KV_NS, *args)

    def _persist(self) -> None:
        """Write apps (specs+edges), routes, and live replica ids. Small
        state, rewritten whole per mutation — crash-consistent because the
        restore path health-checks every adopted replica anyway."""
        try:
            with self._lock:
                apps = {
                    app: {
                        "specs": [d["spec"] for d in deps.values()],
                        "edges": {
                            name: d.get("edges", []) for name, d in deps.items()
                        },
                    }
                    for app, deps in self.apps.items()
                }
                rids = {
                    app: {
                        name: [r._actor_id.hex() for r in d["replicas"]]
                        for name, d in deps.items()
                    }
                    for app, deps in self.apps.items()
                }
                routes = dict(self.routes)
            self._kv_call("kv_put", _KV_APPS, cloudpickle.dumps(apps), True)
            self._kv_call("kv_put", _KV_REPLICAS, cloudpickle.dumps(rids), True)
            self._kv_call("kv_put", _KV_ROUTES, cloudpickle.dumps(routes), True)
        except Exception:
            logger.exception("serve controller: state persist failed")

    def _clear_persisted(self) -> None:
        for key in (_KV_APPS, _KV_REPLICAS, _KV_ROUTES, _KV_DRAINING):
            try:
                self._kv_call("kv_del", key)
            except Exception:
                pass

    def _persist_draining(self) -> None:
        """The drain queue must survive a controller crash: an orphaned
        DRAINING replica rejects all work but holds its worker process and
        ports forever (nothing else would ever kill it). Deadlines persist
        as wall-clock (monotonic doesn't cross processes)."""
        try:
            now_mono = time.monotonic()
            now_wall = time.time()
            with self._drain_lock:
                entries = [
                    {
                        "rid": e["rid"],
                        "app": e["app"],
                        "deployment": e["deployment"],
                        "expires_at": now_wall + max(0.0, e["deadline"] - now_mono),
                    }
                    for e in self._draining
                ]
            self._kv_call(
                "kv_put", _KV_DRAINING, cloudpickle.dumps(entries), True
            )
        except Exception:
            logger.exception("serve controller: drain-queue persist failed")

    def _restore_draining(self) -> None:
        from ray_tpu._private.ids import ActorID
        from ray_tpu.actor import _DynamicActorHandle

        try:
            blob = self._kv_call("kv_get", _KV_DRAINING)
            if not blob:
                return
            entries = cloudpickle.loads(blob)
        except Exception:
            logger.exception("serve controller: drain-queue restore failed")
            return
        now_mono = time.monotonic()
        now_wall = time.time()
        restored = []
        for e in entries:
            try:
                replica = _DynamicActorHandle(ActorID.from_hex(e["rid"]))
            except Exception:
                continue
            restored.append(
                {
                    "replica": replica,
                    "rid": e["rid"],
                    "deadline": now_mono
                    + max(0.0, e.get("expires_at", now_wall) - now_wall),
                    "app": e.get("app", "?"),
                    "deployment": e.get("deployment", "?"),
                }
            )
        if restored:
            with self._drain_lock:
                self._draining.extend(restored)

    def _restore_state(self) -> None:
        """Recover apps/routes from the KV and re-adopt live replicas."""
        self._restore_draining()  # independent of apps: pending retirements
        try:
            blob = self._kv_call("kv_get", _KV_APPS)
            if not blob:
                return
            apps = cloudpickle.loads(blob)
            rblob = self._kv_call("kv_get", _KV_REPLICAS)
            rids_map = cloudpickle.loads(rblob) if rblob else {}
            routes_blob = self._kv_call("kv_get", _KV_ROUTES)
            self.routes = cloudpickle.loads(routes_blob) if routes_blob else {}
        except Exception:
            logger.exception("serve controller: state restore failed; starting empty")
            return
        adopted_total = 0
        for app_name, payload in apps.items():
            try:
                deployments: Dict[str, dict] = {}
                handles: Dict[str, DeploymentHandle] = {}
                for spec in payload["specs"]:
                    name = spec["name"]
                    edges = payload["edges"].get(name, [])
                    init_args = list(spec["init_args"])
                    init_kwargs = dict(spec["init_kwargs"])
                    for key, child in edges:
                        if isinstance(key, int):
                            init_args[key] = handles[child]
                        else:
                            init_kwargs[key] = handles[child]
                    adopted = self._adopt_replicas(
                        rids_map.get(app_name, {}).get(name, [])
                    )
                    adopted_total += len(adopted)
                    deployments[name] = {
                        "spec": spec,
                        "init_args": init_args,
                        "init_kwargs": init_kwargs,
                        "edges": edges,
                        "replicas": adopted,
                        "health": "HEALTHY" if adopted else "UNHEALTHY",
                    }
                    handles[name] = DeploymentHandle(
                        name, app_name, adopted, config=_handle_config(spec)
                    )
                self.apps[app_name] = deployments
            except Exception:
                logger.exception(
                    "serve controller: could not restore app %r", app_name
                )
        if self.apps:
            _event(
                "SERVE_CONTROLLER_RECOVERED",
                f"controller restored {len(self.apps)} app(s), re-adopted "
                f"{adopted_total} live replica(s); reconcile will top up the rest",
                severity="WARNING",
                apps=sorted(self.apps),
                adopted_replicas=adopted_total,
            )

    @staticmethod
    def _adopt_replicas(rid_hexes: List[str]) -> List[Any]:
        """Health-check persisted replica ids; return handles for the ones
        still alive (the whole point of controller FT: don't cold-start)."""
        from ray_tpu._private.ids import ActorID
        from ray_tpu.actor import _DynamicActorHandle

        candidates = []
        for h in rid_hexes:
            try:
                candidates.append(_DynamicActorHandle(ActorID.from_hex(h)))
            except Exception:
                continue
        refs = []
        for r in candidates:
            try:
                refs.append(r.check_health.remote())
            except Exception:
                refs.append(None)
        alive = []
        deadline = time.monotonic() + 10.0
        for r, ref in zip(candidates, refs):
            if ref is None:
                continue
            try:
                ray_tpu.get(ref, timeout=max(0.5, deadline - time.monotonic()))
                alive.append(r)
            except Exception:
                continue
        return alive

    # -- deploy ------------------------------------------------------------

    def deploy_application(self, app_name: str, specs: List[dict], edges: Dict[str, List]):
        """specs are topologically ordered; edges[name] = list of
        (arg_index_or_kwarg, child_name) to replace with handles."""
        deployments: Dict[str, dict] = {}
        handles: Dict[str, DeploymentHandle] = {}
        consumed: set = set()  # deployments whose replicas carried over
        with self._lock:
            live = self.apps.get(app_name) or {}
        for spec in specs:
            name = spec["name"]
            init_args = list(spec["init_args"])
            init_kwargs = dict(spec["init_kwargs"])
            for key, child in edges.get(name, []):
                if isinstance(key, int):
                    init_args[key] = handles[child]
                else:
                    init_kwargs[key] = handles[child]
            prev = live.get(name)
            if prev is not None and self._only_user_config_changed(prev["spec"], spec):
                # lightweight update (parity: deployment_state.py): push the
                # new user_config to live replicas via reconfigure() instead
                # of restarting them. The live table is NOT mutated here — a
                # later failure in this deploy leaves it fully consistent.
                replicas = list(prev["replicas"])
                ray_tpu.get(
                    [r.reconfigure.remote(spec["user_config"]) for r in replicas],
                    timeout=120,
                )
                consumed.add(name)
                deployments[name] = {
                    "spec": spec,
                    "init_args": init_args,
                    "init_kwargs": init_kwargs,
                    "edges": edges.get(name, []),
                    "replicas": replicas,
                    "health": prev.get("health", "HEALTHY"),
                }
                handles[name] = DeploymentHandle(
                    name, app_name, replicas, config=_handle_config(spec)
                )
                continue
            replicas = self._start_replicas(spec, init_args, init_kwargs)
            deployments[name] = {
                "spec": spec,
                "init_args": init_args,
                "init_kwargs": init_kwargs,
                "edges": edges.get(name, []),
                "replicas": replicas,
                "health": "HEALTHY",
            }
            handles[name] = DeploymentHandle(
                name, app_name, replicas, config=_handle_config(spec)
            )
        # gracefully retire a previous version of the app (minus deployments
        # whose replicas were carried over by a lightweight user_config
        # update): old replicas drain — finish in-flight work, reject new —
        # and are only killed once idle or past graceful_shutdown_timeout_s
        with self._lock:
            old = self.apps.get(app_name)
            self.apps[app_name] = deployments
        if old:
            self._drain_app(
                app_name, {k: v for k, v in old.items() if k not in consumed}
            )
        self._persist()
        return True

    def _start_replicas(self, spec: dict, init_args, init_kwargs):
        opts = dict(spec["ray_actor_options"])
        max_ongoing = spec["max_ongoing_requests"]
        replicas = []
        for _ in range(spec["num_replicas"]):
            # thread pool larger than the request gate so queued requests
            # are counted (autoscaling metric) and health probes aren't
            # starved by busy request threads
            r = Replica.options(
                max_concurrency=min(64, max_ongoing * 4 + 4),
                num_cpus=opts.get("num_cpus", 0.0),
                num_tpus=opts.get("num_tpus", 0.0),
                resources=opts.get("resources"),
            ).remote(spec["callable_blob"], init_args, init_kwargs, max_ongoing,
                     spec.get("user_config"), spec.get("name", ""))
            replicas.append(r)
        # wait until they respond (surface init errors early)
        ray_tpu.get([r.check_health.remote() for r in replicas], timeout=120)
        return replicas

    @staticmethod
    def _only_user_config_changed(old_spec: dict, new_spec: dict) -> bool:
        keys = set(old_spec) | set(new_spec)
        for k in keys - {"user_config"}:
            try:
                same = bool(old_spec.get(k) == new_spec.get(k))
            except Exception:  # e.g. numpy array args: ambiguous truth value
                same = False
            if not same:
                return False
        try:
            return bool(
                old_spec.get("user_config") != new_spec.get("user_config")
            )
        except Exception:
            return True  # un-comparable configs: deliver the new one

    # -- graceful drain ----------------------------------------------------

    def _drain_app(self, app_name: str, deployments: Dict[str, dict]):
        for name, d in deployments.items():
            self._drain_replicas(app_name, name, d["spec"], d["replicas"])

    def _drain_replicas(self, app_name: str, dep_name: str, spec: dict, replicas):
        """Mark replicas DRAINING and queue them for the drain loop: killed
        once idle (in-flight requests, streams, and websocket sessions have
        finished) or past the deployment's graceful_shutdown_timeout_s."""
        if not replicas:
            return
        timeout = float(spec.get("graceful_shutdown_timeout_s", 20.0) or 0.0)
        deadline = time.monotonic() + timeout
        entries = []
        for r in replicas:
            try:
                r.prepare_drain.remote()  # fire-and-forget: flag flips fast
            except Exception:
                pass
            entries.append(
                {
                    "replica": r,
                    "rid": r._actor_id.hex(),
                    "deadline": deadline,
                    "app": app_name,
                    "deployment": dep_name,
                }
            )
        with self._drain_lock:
            self._draining.extend(entries)
        self._persist_draining()

    def _drain_loop(self):
        while not self._stop:
            time.sleep(self.DRAIN_TICK_S)
            try:
                self._reap_draining_once()
            except Exception:
                logger.exception("serve controller: drain pass failed")

    def _reap_draining_once(self, force_deadline: Optional[float] = None) -> int:
        """One drain pass: kill entries that are idle or expired; returns
        how many remain. ``force_deadline`` overrides per-entry deadlines
        (synchronous shutdown path)."""
        with self._drain_lock:
            entries = list(self._draining)
        if not entries:
            return 0
        # probe all draining replicas in parallel (a hung one must not
        # stall the pass). drain_status is atomic (draining, ongoing): an
        # idle-kill requires the replica to have CONFIRMED the drain flag —
        # otherwise a dispatch racing the fire-and-forget prepare_drain
        # could start executing between our probe and the kill.
        refs = []
        for e in entries:
            try:
                refs.append(e["replica"].drain_status.remote())
            except Exception:
                refs.append(None)
        deadline = time.monotonic() + 5.0
        finished = []
        for e, ref in zip(entries, refs):
            ongoing = None
            draining = False
            dead = ref is None
            if ref is not None:
                try:
                    draining, ongoing = ray_tpu.get(
                        ref, timeout=max(0.5, deadline - time.monotonic())
                    )
                except Exception:
                    dead = True  # dead or unreachable: reap it
            if not dead and not draining:
                # flag not confirmed yet: re-send and wait for next tick
                try:
                    e["replica"].prepare_drain.remote()
                except Exception:
                    pass
            entry_deadline = e["deadline"]
            if force_deadline is not None:
                entry_deadline = min(entry_deadline, force_deadline)
            expired = time.monotonic() > entry_deadline
            if (draining and ongoing == 0) or dead or expired:
                try:
                    ray_tpu.kill(e["replica"])
                except Exception:
                    pass
                _inc("drained", e["deployment"])
                _event(
                    "REPLICA_DRAINED",
                    f"replica {e['rid'][:12]} of {e['app']}/{e['deployment']} "
                    + (
                        "drained idle"
                        if draining and ongoing == 0
                        else (
                            "already dead"
                            if dead and not expired
                            else f"drain timed out with {ongoing} in flight"
                        )
                    ),
                    severity="INFO" if (draining and ongoing == 0) else "WARNING",
                    deployment=e["deployment"],
                    app=e["app"],
                    replica_id=e["rid"],
                )
                finished.append(e["rid"])
        if finished:
            with self._drain_lock:
                self._draining = [
                    e for e in self._draining if e["rid"] not in finished
                ]
            self._persist_draining()
        with self._drain_lock:
            return len(self._draining)

    # -- data-plane discovery ---------------------------------------------

    def get_handle_info(self, app_name: str, deployment_name: Optional[str] = None):
        app = self.apps.get(app_name)
        if app is None:
            return None
        if deployment_name is None:
            deployment_name = next(reversed(app))  # ingress = last deployed
        d = app.get(deployment_name)
        if d is None:
            return None
        # replicas: the serving set only — draining/dead replicas are
        # removed from the table the moment their retirement starts, so
        # handles and proxies stop routing to them on their next refresh.
        # depths: controller-probed queue lengths (parity: the replica
        # queue-len probes of pow_2_scheduler.py:49, amortized through the
        # reconcile loop instead of per-request RPCs)
        return {
            "deployment": deployment_name,
            "replicas": list(d["replicas"]),
            "depths": d.get("depths"),
            "health": d.get("health", "HEALTHY"),
            "config": _handle_config(d["spec"]),
        }

    def register_route(self, route_prefix: str, app_name: str) -> bool:
        self.routes[route_prefix] = app_name
        self._persist()
        return True

    def get_routes(self) -> Dict[str, str]:
        return dict(self.routes)

    def status(self):
        with self._drain_lock:
            draining: Dict[tuple, int] = {}
            for e in self._draining:
                key = (e["app"], e["deployment"])
                draining[key] = draining.get(key, 0) + 1
        out = {}
        for app, deps in self.apps.items():
            out[app] = {}
            for name, d in deps.items():
                spec = d["spec"]
                out[app][name] = {
                    "num_replicas": len(d["replicas"]),
                    "target": spec["num_replicas"],
                    "health": d.get("health", "HEALTHY"),
                    "draining": draining.get((app, name), 0),
                    # controller-aggregated per-deployment request latency
                    # (sliding-window p50/p95/p99 across ALL replicas, with
                    # exemplar trace ids for the slow tail)
                    "latency": d.get("latency"),
                    # stream-TTFT fold (streaming deployments only): the
                    # tracing plane's per-stream first-token spans, rolled
                    # into a per-deployment window — the LLM SLO surface
                    "ttft": d.get("ttft"),
                    # the resilience knobs, surfaced for operators
                    # (docstring: Deployment)
                    "config": _handle_config(spec),
                }
        return out

    def delete_application(self, app_name: str):
        with self._lock:
            app = self.apps.pop(app_name, None)
            doomed_routes = [
                p for p, a in self.routes.items() if a == app_name
            ]
            for p in doomed_routes:
                del self.routes[p]
        # best-effort: stop live proxies from serving the stale routes
        if doomed_routes:
            from ray_tpu.serve._proxy import _PROXY_NAME

            names = [_PROXY_NAME] + [
                f"{_PROXY_NAME}:{n['node_id'][:12]}" for n in ray_tpu.nodes()
            ]
            for name in names:
                try:
                    proxy = ray_tpu.get_actor(name)
                    for p in doomed_routes:
                        proxy.remove_route.remote(p)
                except ValueError:
                    pass
        if app:
            self._drain_app(app_name, app)
        self._persist()
        return True

    def shutdown_all(self):
        self._stop = True
        for app in list(self.apps):
            self.delete_application(app)
        # synchronous bounded drain: the loops are stopping, so reap here
        # until every retired replica is idle-killed or times out
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if self._reap_draining_once(force_deadline=deadline) == 0:
                break
            time.sleep(self.DRAIN_TICK_S)
        # expire stragglers immediately
        self._reap_draining_once(force_deadline=0.0)
        self._clear_persisted()
        return True

    def _autoscale_target(self, d: dict, alive, depths) -> None:
        """Queue-depth autoscaling (parity: serve autoscaling_policy.py):
        desired = clamp(ceil(total_ongoing / target), min, max), where
        total_ongoing is the replicas' queued+running depth. Only moves the
        TARGET; the reconcile pass starts/drains replicas toward it.

        With ``target_ttft_ms`` set, the folded stream-TTFT window acts as
        a second scale-UP signal: a p99 TTFT above target asks for one more
        replica even when queue depths look fine (decode slots saturated by
        long streams rather than queued requests). TTFT never scales down —
        an idle deployment has no TTFT samples, only depths."""
        cfg = d["spec"].get("autoscaling_config")
        if not cfg or not alive or depths is None:
            return
        total = sum(depths)
        target = float(cfg.get("target_ongoing_requests", 2.0))
        lo = int(cfg.get("min_replicas", 1))
        hi = int(cfg.get("max_replicas", max(lo, 1)))
        import math

        desired = max(lo, min(hi, math.ceil(total / max(target, 1e-9)) or lo))
        ttft_target = cfg.get("target_ttft_ms")
        if ttft_target is not None:
            snap = d.get("ttft") or {}
            p99 = snap.get("p99")
            if snap.get("count", 0) >= int(cfg.get("ttft_min_samples", 5)) and (
                p99 is not None and float(p99) > float(ttft_target)
            ):
                desired = max(desired, min(hi, len(alive) + 1))
        d["spec"]["num_replicas"] = desired

    # -- reconciliation (parity: DeploymentState reconcile loop) ----------

    def _reconcile_loop(self):
        failures = 0
        while not self._stop:
            time.sleep(self.RECONCILE_TICK_S)
            try:
                self._reconcile_once()
                failures = 0
            except Exception as e:
                # a reconcile crash must be loud (it silently disabled
                # healing before) and must not hot-loop
                failures += 1
                logger.exception("serve controller: reconcile pass failed")
                _event(
                    "SERVE_RECONCILE_ERROR",
                    f"reconcile pass failed ({failures} consecutive): "
                    f"{type(e).__name__}: {e}",
                    severity="ERROR",
                    consecutive_failures=failures,
                )
                time.sleep(min(0.5 * (2 ** min(failures, 6)), 30.0))

    def _reconcile_once(self):
        now = time.monotonic()
        with self._lock:
            snapshot = list(self.apps.items())
        # select deployments whose probe period elapsed, then fan ALL their
        # health probes out before collecting any (one hung replica costs
        # the shared budget, not 10s x replicas serially)
        due = []
        for app_name, deployments in snapshot:
            for name, d in deployments.items():
                period = float(d["spec"].get("health_check_period_s", 5.0) or 5.0)
                if now >= d.get("_next_probe", 0.0):
                    d["_next_probe"] = now + period
                    replicas = list(d["replicas"])
                    refs = []
                    for r in replicas:
                        try:
                            refs.append(r.check_health.remote())
                        except Exception:
                            refs.append(None)
                    due.append((app_name, name, d, replicas, refs))
        if not due:
            return
        probe_deadline = time.monotonic() + self.PROBE_BUDGET_S
        for app_name, name, d, replicas, refs in due:
            alive = []
            for r, ref in zip(replicas, refs):
                ok = False
                if ref is not None:
                    try:
                        ray_tpu.get(
                            ref,
                            timeout=max(0.5, probe_deadline - time.monotonic()),
                        )
                        ok = True
                    except Exception:
                        ok = False
                if ok:
                    alive.append(r)
                else:
                    _inc("deaths", name)
                    _event(
                        "REPLICA_DIED",
                        f"replica {r._actor_id.hex()[:12]} of "
                        f"{app_name}/{name} failed its health probe",
                        severity="ERROR",
                        deployment=name,
                        app=app_name,
                        replica_id=r._actor_id.hex(),
                    )
            # probe queue depths once per pass: feeds both autoscaling
            # and the handles' probed pow-2 routing (via get_handle_info)
            depths = None
            try:
                depth_refs = [r.num_ongoing.remote() for r in alive]
                depths = ray_tpu.get(
                    depth_refs,
                    timeout=max(0.5, probe_deadline - time.monotonic()),
                )
            except Exception:
                pass
            # keyed by replica id: stays correct across drains/refreshes
            d["depths"] = (
                {
                    r._actor_id.hex(): depth
                    for r, depth in zip(alive, depths)
                }
                if depths is not None
                else None
            )
            # per-DEPLOYMENT latency aggregation: fold every replica's
            # sliding-window samples (with exemplar trace ids) into one
            # window — the per-replica histograms only tell half the story
            try:
                sample_refs = [r.latency_samples.remote() for r in alive]
                all_samples = ray_tpu.get(
                    sample_refs,
                    timeout=max(0.5, probe_deadline - time.monotonic()),
                )
                from ray_tpu._private.telemetry import LatencyWindow
                from ray_tpu._private.worker import get_runtime

                win = LatencyWindow(
                    window_s=float(
                        getattr(
                            get_runtime().config, "latency_window_s", 60.0
                        )
                    )
                )
                for samples in all_samples:
                    if samples:
                        win.merge_from(samples)
                d["latency"] = win.snapshot()
            except Exception:
                pass
            # stream-TTFT aggregation (same fold, separate window): the
            # per-deployment p50/p99 TTFT shown by serve.status() and the
            # TTFT-driven autoscaling signal (target_ttft_ms)
            try:
                ttft_refs = [r.ttft_samples.remote() for r in alive]
                all_ttft = ray_tpu.get(
                    ttft_refs,
                    timeout=max(0.5, probe_deadline - time.monotonic()),
                )
                from ray_tpu._private.telemetry import LatencyWindow as _LW
                from ray_tpu._private.worker import get_runtime as _grt

                twin = _LW(
                    window_s=float(
                        getattr(_grt().config, "latency_window_s", 60.0)
                    )
                )
                for samples in all_ttft:
                    if samples:
                        twin.merge_from(samples)
                d["ttft"] = twin.snapshot()
            except Exception:
                pass
            # health state vs the PRE-autoscale target and BEFORE repair:
            # replica deaths are the forensics signal, an autoscale-up gap
            # is not
            self._update_health(
                app_name, name, d, len(alive), d["spec"]["num_replicas"]
            )
            self._autoscale_target(d, alive, depths)
            want = d["spec"]["num_replicas"]
            if len(alive) > want:
                # scale-down (autoscale or adoption overflow): gracefully
                # drain the idlest extras instead of killing mid-request
                order = sorted(
                    range(len(alive)),
                    key=lambda i: depths[i] if depths else 0,
                )
                drop = set(order[: len(alive) - want])
                self._drain_replicas(
                    app_name, name, d["spec"], [alive[i] for i in drop]
                )
                alive = [r for i, r in enumerate(alive) if i not in drop]
            fresh = []
            if len(alive) < want:
                fresh = self._start_replicas(
                    {**d["spec"], "num_replicas": want - len(alive)},
                    d["init_args"],
                    d["init_kwargs"],
                )
            # only commit if this app/deployment is still current —
            # a concurrent redeploy/delete must not get replicas
            # resurrected into its orphaned table
            changed = bool(fresh) or len(alive) != len(replicas)
            with self._lock:
                current = self.apps.get(app_name)
                if current is not None and current.get(name) is d:
                    d["replicas"] = alive + fresh
                else:
                    for r in fresh:
                        try:
                            ray_tpu.kill(r)
                        except Exception:
                            pass
                    changed = False
            if changed:
                self._persist()

    def _update_health(self, app_name: str, name: str, d: dict,
                       n_alive: int, want: int) -> None:
        if want <= 0 or n_alive >= want:
            health = "HEALTHY"
        elif n_alive == 0:
            health = "UNHEALTHY"
        else:
            health = "DEGRADED"
        prev = d.get("health", "HEALTHY")
        d["health"] = health
        if health == prev:
            return
        if health == "HEALTHY":
            _event(
                "DEPLOYMENT_HEALTHY",
                f"deployment {app_name}/{name} recovered ({n_alive}/{want})",
                severity="INFO",
                deployment=name,
                app=app_name,
            )
        else:
            _event(
                "DEPLOYMENT_UNHEALTHY",
                f"deployment {app_name}/{name} is {health} "
                f"({n_alive}/{want} replicas alive)",
                severity="ERROR" if health == "UNHEALTHY" else "WARNING",
                deployment=name,
                app=app_name,
                health=health,
                alive=n_alive,
                target=want,
            )


# --------------------------------------------------------------------------
# module-level API
# --------------------------------------------------------------------------


def _get_or_create_controller():
    try:
        return ray_tpu.get_actor(_CONTROLLER_NAME)
    except ValueError:
        pass
    try:
        # detached + infinitely restartable: survives its creating driver,
        # auto-restarts after a crash (fresh incarnation restores from the
        # KV), and rides the head snapshot across head restarts
        return ServeController.options(
            name=_CONTROLLER_NAME,
            num_cpus=0,
            lifetime="detached",
            max_restarts=-1,
        ).remote()
    except ValueError:
        return ray_tpu.get_actor(_CONTROLLER_NAME)


def _flatten_graph(app: Application):
    """DFS the bound graph; returns (ordered specs, edges)."""
    specs: List[dict] = []
    edges: Dict[str, List] = {}
    seen: Dict[int, str] = {}

    def visit(node: Application) -> str:
        if id(node) in seen:
            return seen[id(node)]
        name = node.deployment.name
        my_edges = []
        args = []
        for i, a in enumerate(node.args):
            if isinstance(a, Application):
                child = visit(a)
                my_edges.append((i, child))
                args.append(None)
            else:
                args.append(a)
        kwargs = {}
        for k, v in node.kwargs.items():
            if isinstance(v, Application):
                child = visit(v)
                my_edges.append((k, child))
                kwargs[k] = None
            else:
                kwargs[k] = v
        spec = node.deployment.spec()
        spec["init_args"] = args
        spec["init_kwargs"] = kwargs
        specs.append(spec)
        edges[name] = my_edges
        seen[id(node)] = name
        return name

    visit(app)
    return specs, edges


def run(app: Application, *, name: str = "default", route_prefix: Optional[str] = None,
        _blocking: bool = True) -> DeploymentHandle:
    if not isinstance(app, Application):
        raise TypeError("serve.run expects a bound deployment: use .bind()")
    controller = _get_or_create_controller()
    specs, edges = _flatten_graph(app)
    ray_tpu.get(controller.deploy_application.remote(name, specs, edges), timeout=180)
    if route_prefix is not None:
        from ray_tpu.serve._proxy import ensure_proxy

        ensure_proxy(controller, name, route_prefix)
    return get_app_handle(name)


def _handle_from_info(app_name: str, info: dict) -> DeploymentHandle:
    return DeploymentHandle(
        info["deployment"],
        app_name,
        info["replicas"],
        config=info.get("config"),
    )


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = ray_tpu.get_actor(_CONTROLLER_NAME)
    info = ray_tpu.get(controller.get_handle_info.remote(name), timeout=60)
    if info is None:
        raise ValueError(f"no serve application named '{name}'")
    return _handle_from_info(name, info)


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    controller = ray_tpu.get_actor(_CONTROLLER_NAME)
    info = ray_tpu.get(
        controller.get_handle_info.remote(app_name, deployment_name), timeout=60
    )
    if info is None:
        raise ValueError(f"no deployment '{deployment_name}' in app '{app_name}'")
    return _handle_from_info(app_name, info)


def status() -> dict:
    controller = ray_tpu.get_actor(_CONTROLLER_NAME)
    return ray_tpu.get(controller.status.remote(), timeout=60)


def delete(name: str):
    controller = ray_tpu.get_actor(_CONTROLLER_NAME)
    ray_tpu.get(controller.delete_application.remote(name), timeout=60)


def shutdown():
    try:
        controller = ray_tpu.get_actor(_CONTROLLER_NAME)
    except ValueError:
        return
    try:
        ray_tpu.get(controller.shutdown_all.remote(), timeout=60)
    except Exception:
        pass
    try:
        ray_tpu.kill(controller)
    except Exception:
        pass
