"""Serve public API: deployments, applications, run/shutdown.

Parity: ``python/ray/serve/api.py`` (``serve.run`` ``:535``) +
``ServeController`` (``_private/controller.py:86``): a detached named
controller actor owns the deployment table and reconciles replica actors
(restart on death); ``.bind()`` builds composition graphs whose nested nodes
become DeploymentHandles (``deployment_graph_build.py``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

import ray_tpu
from ray_tpu.serve._replica import Replica
from ray_tpu.serve.handle import DeploymentHandle

_CONTROLLER_NAME = "SERVE_CONTROLLER"


@dataclass
class Application:
    """A bound deployment graph node."""

    deployment: "Deployment"
    args: tuple
    kwargs: dict


class Deployment:
    def __init__(self, target, *, name=None, num_replicas=1, max_ongoing_requests=8,
                 ray_actor_options=None, health_check_period_s=5.0,
                 autoscaling_config=None, user_config=None):
        self._target = target
        self.name = name or getattr(target, "__name__", "deployment")
        self.num_replicas = num_replicas
        self.max_ongoing_requests = max_ongoing_requests
        self.ray_actor_options = ray_actor_options or {}
        self.health_check_period_s = health_check_period_s
        # {"min_replicas", "max_replicas", "target_ongoing_requests"}
        # (parity: serve autoscaling_policy.py / autoscaling_state.py)
        self.autoscaling_config = dict(autoscaling_config or {}) or None
        # opaque config delivered to the callable's reconfigure() — updating
        # ONLY this on redeploy is a lightweight update (no replica restart)
        self.user_config = user_config

    def options(self, **updates) -> "Deployment":
        new = Deployment(
            self._target,
            name=updates.get("name", self.name),
            num_replicas=updates.get("num_replicas", self.num_replicas),
            max_ongoing_requests=updates.get("max_ongoing_requests", self.max_ongoing_requests),
            ray_actor_options=updates.get("ray_actor_options", self.ray_actor_options),
            health_check_period_s=updates.get(
                "health_check_period_s", self.health_check_period_s
            ),
            autoscaling_config=updates.get("autoscaling_config", self.autoscaling_config),
            user_config=updates.get("user_config", self.user_config),
        )
        return new

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def spec(self) -> dict:
        num = self.num_replicas
        if self.autoscaling_config:
            num = int(self.autoscaling_config.get("min_replicas", 1)) or 1
        return {
            "name": self.name,
            "callable_blob": cloudpickle.dumps(self._target),
            "num_replicas": num,
            "max_ongoing_requests": self.max_ongoing_requests,
            "ray_actor_options": self.ray_actor_options,
            "autoscaling_config": self.autoscaling_config,
            "user_config": self.user_config,
        }


def deployment(target=None, **options):
    """``@serve.deployment`` decorator (parity: ``api.py``)."""
    if target is not None and callable(target):
        return Deployment(target)

    def wrap(t):
        return Deployment(t, **options)

    return wrap


@ray_tpu.remote(max_concurrency=8)
class ServeController:
    """Control plane: deployment table + replica reconciliation."""

    def __init__(self):
        import threading

        # app -> deployment name -> {spec, replicas: [handles]}
        self.apps: Dict[str, Dict[str, dict]] = {}
        # route_prefix -> app name (pushed to every proxy, incl. per-node)
        self.routes: Dict[str, str] = {}
        self._stop = False
        # guards self.apps mutations against the reconciler thread (this actor
        # is threaded, so handlers run concurrently)
        self._lock = threading.Lock()
        self._reconciler = threading.Thread(target=self._reconcile_loop, daemon=True)
        self._reconciler.start()

    # -- deploy ------------------------------------------------------------

    def deploy_application(self, app_name: str, specs: List[dict], edges: Dict[str, List]):
        """specs are topologically ordered; edges[name] = list of
        (arg_index_or_kwarg, child_name) to replace with handles."""
        deployments: Dict[str, dict] = {}
        handles: Dict[str, DeploymentHandle] = {}
        consumed: set = set()  # deployments whose replicas carried over
        with self._lock:
            live = self.apps.get(app_name) or {}
        for spec in specs:
            name = spec["name"]
            init_args = list(spec["init_args"])
            init_kwargs = dict(spec["init_kwargs"])
            for key, child in edges.get(name, []):
                if isinstance(key, int):
                    init_args[key] = handles[child]
                else:
                    init_kwargs[key] = handles[child]
            prev = live.get(name)
            if prev is not None and self._only_user_config_changed(prev["spec"], spec):
                # lightweight update (parity: deployment_state.py): push the
                # new user_config to live replicas via reconfigure() instead
                # of restarting them. The live table is NOT mutated here — a
                # later failure in this deploy leaves it fully consistent.
                replicas = list(prev["replicas"])
                ray_tpu.get(
                    [r.reconfigure.remote(spec["user_config"]) for r in replicas],
                    timeout=120,
                )
                consumed.add(name)
                deployments[name] = {
                    "spec": spec,
                    "init_args": init_args,
                    "init_kwargs": init_kwargs,
                    "replicas": replicas,
                }
                handles[name] = DeploymentHandle(name, app_name, replicas)
                continue
            replicas = self._start_replicas(spec, init_args, init_kwargs)
            deployments[name] = {
                "spec": spec,
                "init_args": init_args,
                "init_kwargs": init_kwargs,
                "replicas": replicas,
            }
            handles[name] = DeploymentHandle(name, app_name, replicas)
        # tear down a previous version of the app (minus deployments whose
        # replicas were carried over by a lightweight user_config update)
        with self._lock:
            old = self.apps.get(app_name)
            self.apps[app_name] = deployments
        if old:
            self._teardown({k: v for k, v in old.items() if k not in consumed})
        return True

    def _start_replicas(self, spec: dict, init_args, init_kwargs):
        opts = dict(spec["ray_actor_options"])
        max_ongoing = spec["max_ongoing_requests"]
        replicas = []
        for _ in range(spec["num_replicas"]):
            # thread pool larger than the request gate so queued requests
            # are counted (autoscaling metric) and health probes aren't
            # starved by busy request threads
            r = Replica.options(
                max_concurrency=min(64, max_ongoing * 4 + 4),
                num_cpus=opts.get("num_cpus", 0.0),
                num_tpus=opts.get("num_tpus", 0.0),
                resources=opts.get("resources"),
            ).remote(spec["callable_blob"], init_args, init_kwargs, max_ongoing,
                     spec.get("user_config"), spec.get("name", ""))
            replicas.append(r)
        # wait until they respond (surface init errors early)
        ray_tpu.get([r.check_health.remote() for r in replicas], timeout=120)
        return replicas

    @staticmethod
    def _only_user_config_changed(old_spec: dict, new_spec: dict) -> bool:
        keys = set(old_spec) | set(new_spec)
        for k in keys - {"user_config"}:
            try:
                same = bool(old_spec.get(k) == new_spec.get(k))
            except Exception:  # e.g. numpy array args: ambiguous truth value
                same = False
            if not same:
                return False
        try:
            return bool(
                old_spec.get("user_config") != new_spec.get("user_config")
            )
        except Exception:
            return True  # un-comparable configs: deliver the new one

    def _teardown(self, deployments: Dict[str, dict]):
        for d in deployments.values():
            for r in d["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass

    # -- data-plane discovery ---------------------------------------------

    def get_handle_info(self, app_name: str, deployment_name: Optional[str] = None):
        app = self.apps.get(app_name)
        if app is None:
            return None
        if deployment_name is None:
            deployment_name = next(reversed(app))  # ingress = last deployed
        d = app.get(deployment_name)
        if d is None:
            return None
        # depths: controller-probed queue lengths (parity: the replica
        # queue-len probes of pow_2_scheduler.py:49, amortized through the
        # reconcile loop instead of per-request RPCs)
        return (deployment_name, d["replicas"], d.get("depths"))

    def register_route(self, route_prefix: str, app_name: str) -> bool:
        self.routes[route_prefix] = app_name
        return True

    def get_routes(self) -> Dict[str, str]:
        return dict(self.routes)

    def status(self):
        return {
            app: {
                name: {
                    "num_replicas": len(d["replicas"]),
                    "target": d["spec"]["num_replicas"],
                }
                for name, d in deps.items()
            }
            for app, deps in self.apps.items()
        }

    def delete_application(self, app_name: str):
        with self._lock:
            app = self.apps.pop(app_name, None)
            doomed_routes = [
                p for p, a in self.routes.items() if a == app_name
            ]
            for p in doomed_routes:
                del self.routes[p]
        # best-effort: stop live proxies from serving the stale routes
        if doomed_routes:
            from ray_tpu.serve._proxy import _PROXY_NAME

            names = [_PROXY_NAME] + [
                f"{_PROXY_NAME}:{n['node_id'][:12]}" for n in ray_tpu.nodes()
            ]
            for name in names:
                try:
                    proxy = ray_tpu.get_actor(name)
                    for p in doomed_routes:
                        proxy.remove_route.remote(p)
                except ValueError:
                    pass
        if app:
            self._teardown(app)
        return True

    def shutdown_all(self):
        self._stop = True
        for app in list(self.apps):
            self.delete_application(app)
        return True

    def _autoscale(self, d: dict, alive, depths):
        """Queue-depth autoscaling (parity: serve autoscaling_policy.py):
        desired = clamp(ceil(total_ongoing / target), min, max), where
        total_ongoing is the replicas' queued+running depth."""
        cfg = d["spec"].get("autoscaling_config")
        if not cfg or not alive or depths is None:
            return alive
        total = sum(depths)
        target = float(cfg.get("target_ongoing_requests", 2.0))
        lo = int(cfg.get("min_replicas", 1))
        hi = int(cfg.get("max_replicas", max(lo, 1)))
        import math

        desired = max(lo, min(hi, math.ceil(total / max(target, 1e-9)) or lo))
        current = d["spec"]["num_replicas"]
        if desired > current:
            d["spec"]["num_replicas"] = desired  # reconcile starts the rest
        elif desired < current:
            d["spec"]["num_replicas"] = desired
            # drain the idlest replicas: remove them from the serving table
            # now (handles stop routing on refresh), kill once idle or after
            # a grace period — an immediate kill loses in-flight requests
            order = sorted(range(len(alive)), key=lambda i: depths[i])
            drop = set(order[: len(alive) - desired])
            draining = d.setdefault("draining", [])
            for i in drop:
                draining.append((alive[i], time.monotonic() + 15.0))
            alive = [r for i, r in enumerate(alive) if i not in drop]
        self._reap_draining(d)
        return alive

    def _reap_draining(self, d: dict):
        still = []
        for r, deadline in d.get("draining", []):
            idle = False
            try:
                idle = ray_tpu.get(r.num_ongoing.remote(), timeout=5) == 0
            except Exception:
                idle = True  # already dead
            if idle or time.monotonic() > deadline:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
            else:
                still.append((r, deadline))
        if "draining" in d:
            d["draining"] = still

    # -- reconciliation (parity: DeploymentState reconcile loop) ----------

    def _reconcile_loop(self):
        while not self._stop:
            time.sleep(1.0)
            try:
                self._reconcile_once()
            except Exception:
                pass

    def _reconcile_once(self):
        with self._lock:
            snapshot = list(self.apps.items())
        for app_name, deployments in snapshot:
            for name, d in deployments.items():
                alive = []
                for r in list(d["replicas"]):
                    try:
                        ray_tpu.get(r.check_health.remote(), timeout=10)
                        alive.append(r)
                    except Exception:
                        pass
                # probe queue depths once per pass: feeds both autoscaling
                # and the handles' probed pow-2 routing (via get_handle_info)
                depths = None
                try:
                    depths = ray_tpu.get(
                        [r.num_ongoing.remote() for r in alive], timeout=10
                    )
                except Exception:
                    pass
                # keyed by replica id: stays correct across drains/refreshes
                d["depths"] = (
                    {
                        r._actor_id.hex(): depth
                        for r, depth in zip(alive, depths)
                    }
                    if depths is not None
                    else None
                )
                alive = self._autoscale(d, alive, depths)
                want = d["spec"]["num_replicas"]
                fresh = []
                if len(alive) < want:
                    fresh = self._start_replicas(
                        {**d["spec"], "num_replicas": want - len(alive)},
                        d["init_args"],
                        d["init_kwargs"],
                    )
                # only commit if this app/deployment is still current —
                # a concurrent redeploy/delete must not get replicas
                # resurrected into its orphaned table
                with self._lock:
                    current = self.apps.get(app_name)
                    if current is not None and current.get(name) is d:
                        d["replicas"] = alive + fresh
                    else:
                        for r in fresh:
                            try:
                                ray_tpu.kill(r)
                            except Exception:
                                pass


# --------------------------------------------------------------------------
# module-level API
# --------------------------------------------------------------------------


def _get_or_create_controller():
    try:
        return ray_tpu.get_actor(_CONTROLLER_NAME)
    except ValueError:
        pass
    try:
        return ServeController.options(name=_CONTROLLER_NAME, num_cpus=0).remote()
    except ValueError:
        return ray_tpu.get_actor(_CONTROLLER_NAME)


def _flatten_graph(app: Application):
    """DFS the bound graph; returns (ordered specs, edges)."""
    specs: List[dict] = []
    edges: Dict[str, List] = {}
    seen: Dict[int, str] = {}

    def visit(node: Application) -> str:
        if id(node) in seen:
            return seen[id(node)]
        name = node.deployment.name
        my_edges = []
        args = []
        for i, a in enumerate(node.args):
            if isinstance(a, Application):
                child = visit(a)
                my_edges.append((i, child))
                args.append(None)
            else:
                args.append(a)
        kwargs = {}
        for k, v in node.kwargs.items():
            if isinstance(v, Application):
                child = visit(v)
                my_edges.append((k, child))
                kwargs[k] = None
            else:
                kwargs[k] = v
        spec = node.deployment.spec()
        spec["init_args"] = args
        spec["init_kwargs"] = kwargs
        specs.append(spec)
        edges[name] = my_edges
        seen[id(node)] = name
        return name

    visit(app)
    return specs, edges


def run(app: Application, *, name: str = "default", route_prefix: Optional[str] = None,
        _blocking: bool = True) -> DeploymentHandle:
    if not isinstance(app, Application):
        raise TypeError("serve.run expects a bound deployment: use .bind()")
    controller = _get_or_create_controller()
    specs, edges = _flatten_graph(app)
    ray_tpu.get(controller.deploy_application.remote(name, specs, edges), timeout=180)
    if route_prefix is not None:
        from ray_tpu.serve._proxy import ensure_proxy

        ensure_proxy(controller, name, route_prefix)
    return get_app_handle(name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = ray_tpu.get_actor(_CONTROLLER_NAME)
    info = ray_tpu.get(controller.get_handle_info.remote(name), timeout=60)
    if info is None:
        raise ValueError(f"no serve application named '{name}'")
    dep_name, replicas = info[0], info[1]
    return DeploymentHandle(dep_name, name, replicas)


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    controller = ray_tpu.get_actor(_CONTROLLER_NAME)
    info = ray_tpu.get(
        controller.get_handle_info.remote(app_name, deployment_name), timeout=60
    )
    if info is None:
        raise ValueError(f"no deployment '{deployment_name}' in app '{app_name}'")
    dep_name, replicas = info[0], info[1]
    return DeploymentHandle(dep_name, app_name, replicas)


def status() -> dict:
    controller = ray_tpu.get_actor(_CONTROLLER_NAME)
    return ray_tpu.get(controller.status.remote(), timeout=60)


def delete(name: str):
    controller = ray_tpu.get_actor(_CONTROLLER_NAME)
    ray_tpu.get(controller.delete_application.remote(name), timeout=60)


def shutdown():
    try:
        controller = ray_tpu.get_actor(_CONTROLLER_NAME)
    except ValueError:
        return
    try:
        ray_tpu.get(controller.shutdown_all.remote(), timeout=60)
    except Exception:
        pass
    try:
        ray_tpu.kill(controller)
    except Exception:
        pass
