"""Continuous-batching inference engine: the in-replica serving loop.

One background thread runs the schedule vLLM popularised — prefill new
requests as decode-batch slots free up, then advance every running
sequence one token per step:

* **prefill/decode split** — each admitted request is prefilled alone at
  a power-of-two padded length (one compile per bucket), emitting its
  first token (the stream's TTFT); decode then runs at a fixed
  ``max_batch`` with inactive slots masked to the null block, so there is
  exactly ONE compiled decode step regardless of which sequences occupy
  the slots.
* **in-flight batching** — new requests join the running batch at step
  boundaries; nobody waits for a "batch" to form or drain.
* **immediate reclamation** — a finished sequence frees its KV blocks at
  the step boundary it finishes on, not when its batch cohort ends.
* **KV-aware admission** — ``submit`` reserves a request's worst-case
  block need (prompt + max_new_tokens) up front; when the reservation
  cannot fit, it sheds with the serve plane's typed
  :class:`DeploymentOverloadedError` (-> HTTP 503 + Retry-After at the
  proxy) instead of queueing into a guaranteed stall. Admitted sequences
  can therefore never deadlock on allocation.

The fixed decode shape also buys schedule-invariance: a sequence's
tokens depend only on its own prompt and (seed, step) PRNG stream, never
on which neighbours share the batch — continuous batching is tokenwise
identical to isolated decode (tested).
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu.serve.exceptions import DeploymentOverloadedError
from ray_tpu.serve.llm.kv_cache import BlockAllocator, BlockTable

__all__ = ["EngineConfig", "InferenceEngine", "TokenStream"]

# engine telemetry (lazy singletons like the replica's): per-deployment
# occupancy of the two continuous-batching queues plus token/shed counters
_metrics: dict = {}


def _engine_metrics() -> dict:
    if not _metrics:
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        _metrics["running"] = Gauge(
            "ray_tpu_llm_running_seqs",
            "sequences currently holding a decode-batch slot (in-flight "
            "batching occupancy) per LLM deployment",
            tag_keys=("deployment",),
        )
        _metrics["waiting"] = Gauge(
            "ray_tpu_llm_waiting_requests",
            "admitted requests waiting for a decode slot per LLM "
            "deployment (admission-bounded; beyond it requests shed)",
            tag_keys=("deployment",),
        )
        _metrics["tokens"] = Counter(
            "ray_tpu_llm_tokens_total",
            "tokens processed by the engine per deployment and phase "
            "(prefill = prompt tokens cached, decode = tokens generated)",
            tag_keys=("deployment", "phase"),
        )
        _metrics["shed"] = Counter(
            "ray_tpu_llm_shed_total",
            "requests shed by KV-aware admission (free-block reservation "
            "or waiting-queue bound exceeded) per LLM deployment",
            tag_keys=("deployment",),
        )
        _metrics["step"] = Histogram(
            "ray_tpu_llm_decode_step_ms",
            "wall time of one continuous-batching decode step (all active "
            "slots advance one token) per LLM deployment",
            tag_keys=("deployment",),
        )
    return _metrics


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Sizing knobs for one engine instance (one replica).

    ``num_blocks`` includes the reserved null block; usable KV capacity is
    ``(num_blocks - 1) * block_size`` tokens. ``max_waiting`` bounds the
    waiting queue BEYOND currently-free decode slots (``max_waiting=0``
    still admits straight into an idle slot) — with capacity reserved at
    admission, it is a latency bound, not a safety valve.
    """

    block_size: int = 16
    num_blocks: int = 256
    max_batch: int = 4
    max_blocks_per_seq: int = 32
    max_waiting: int = 32
    retry_after_s: float = 1.0
    prefill_bucket_min: int = 8
    idle_poll_s: float = 0.05
    stream_timeout_s: float = 120.0


class _Request:
    __slots__ = (
        "id",
        "prompt",
        "max_new_tokens",
        "temperature",
        "top_k",
        "seed",
        "eos_token",
        "need_blocks",
        "out",
        "submitted_at",
    )

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


class _Running:
    """One occupied decode slot: request + block table + decode state."""

    __slots__ = ("req", "table", "last_token", "generated")

    def __init__(self, req: _Request, table: BlockTable, first_token: int):
        self.req = req
        self.table = table
        self.last_token = first_token
        self.generated = 1


class TokenStream:
    """Per-request consumer handle: iterate tokens as the engine emits
    them. Terminates cleanly at end-of-sequence; engine-side failures
    re-raise here (typed, never a silent hang — a stalled engine trips
    ``stream_timeout_s``)."""

    def __init__(self, request_id: int, timeout_s: float):
        self.request_id = request_id
        self._timeout_s = timeout_s
        self._q: "queue.Queue" = queue.Queue()
        self._submitted_at = time.perf_counter()
        self.ttft_s: Optional[float] = None
        self.finish_reason: Optional[str] = None

    # engine side -------------------------------------------------------
    def _emit(self, token: int) -> None:
        if self.ttft_s is None:
            self.ttft_s = time.perf_counter() - self._submitted_at
        self._q.put(("tok", token))

    def _finish(self, reason: str) -> None:
        self._q.put(("done", reason))

    def _fail(self, error: BaseException) -> None:
        self._q.put(("err", error))

    # consumer side -----------------------------------------------------
    def __iter__(self):
        while True:
            try:
                kind, payload = self._q.get(timeout=self._timeout_s)
            except queue.Empty:
                raise TimeoutError(
                    f"token stream {self.request_id} stalled for "
                    f"{self._timeout_s:g}s"
                ) from None
            if kind == "tok":
                yield payload
            elif kind == "done":
                self.finish_reason = payload
                return
            else:
                raise payload

    def tokens(self) -> List[int]:
        """Drain the stream to completion and return every token."""
        return list(self)


class InferenceEngine:
    """Continuous-batching engine over a paged KV pool (one per replica)."""

    def __init__(
        self,
        params,
        model_cfg,
        engine_cfg: Optional[EngineConfig] = None,
        *,
        deployment: str = "llm",
        start: bool = True,
    ):
        from ray_tpu.models import generation as G

        ecfg = engine_cfg or EngineConfig()
        if ecfg.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.params = params
        self.model_cfg = model_cfg
        self.cfg = ecfg
        self.deployment = deployment
        self._G = G
        self._prefill, self._decode, self._decode_greedy = G.make_paged_fns(
            model_cfg, block_size=ecfg.block_size
        )
        self._pool = G.init_paged_pool(model_cfg, ecfg.num_blocks, ecfg.block_size)
        self._alloc = BlockAllocator(ecfg.num_blocks, ecfg.block_size)
        self._slots: List[Optional[_Running]] = [None] * ecfg.max_batch
        self._waiting: "list[tuple[_Request, TokenStream]]" = []
        self._streams: Dict[int, TokenStream] = {}
        self._committed_blocks = 0
        self._ids = itertools.count()
        self._cv = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.max_context = min(
            ecfg.max_blocks_per_seq * ecfg.block_size, model_cfg.max_seq_len
        )
        self._register_kv_provider()
        if start:
            self.start()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="llm-engine", daemon=True
            )
            self._thread.start()

    def shutdown(self, timeout_s: float = 10.0) -> None:
        """Stop the loop and fail any unfinished streams (typed)."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
        err = RuntimeError("inference engine shut down")
        with self._cv:
            for req, stream in self._waiting:
                self._committed_blocks -= req.need_blocks
                stream._fail(err)
            self._waiting.clear()
            for i, run in enumerate(self._slots):
                if run is not None:
                    run.table.release()
                    self._committed_blocks -= run.req.need_blocks
                    run.req.out._fail(err)
                    self._slots[i] = None
        self._update_gauges()

    # -- admission ------------------------------------------------------

    def submit(
        self,
        prompt: Sequence[int],
        *,
        max_new_tokens: int = 16,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int = 0,
        eos_token: Optional[int] = None,
    ) -> TokenStream:
        """Admit a request (KV-reservation admission control) and return
        its :class:`TokenStream`. Sheds with ``DeploymentOverloadedError``
        when the worst-case block need cannot be reserved or the waiting
        queue is at its bound — fast, typed, never queued into a stall."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        total = len(prompt) + max_new_tokens
        if total > self.max_context:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds engine context {self.max_context} "
                f"(max_blocks_per_seq x block_size, capped by max_seq_len)"
            )
        need = self._alloc.blocks_for_tokens(total)
        usable = self._alloc.num_usable
        with self._cv:
            if self._stop:
                raise RuntimeError("inference engine is shut down")
            free_slots = sum(1 for s in self._slots if s is None)
            overloaded = (
                len(self._waiting) >= self.cfg.max_waiting + free_slots
                or self._committed_blocks + need > usable
            )
            if overloaded:
                try:
                    _engine_metrics()["shed"].inc(
                        tags={"deployment": self.deployment}
                    )
                except Exception:
                    pass
                raise DeploymentOverloadedError(
                    deployment=self.deployment,
                    retry_after_s=self.cfg.retry_after_s,
                    load=self._committed_blocks + need,
                    capacity=usable,
                )
            req = _Request(
                id=next(self._ids),
                prompt=prompt,
                max_new_tokens=int(max_new_tokens),
                temperature=float(temperature),
                top_k=int(top_k),
                seed=int(seed),
                eos_token=eos_token,
                need_blocks=need,
                out=None,
                submitted_at=time.perf_counter(),
            )
            stream = TokenStream(req.id, self.cfg.stream_timeout_s)
            req.out = stream
            self._committed_blocks += need
            self._waiting.append((req, stream))
            self._streams[req.id] = stream
            self._cv.notify_all()
        self._update_gauges()
        return stream

    # -- stats ----------------------------------------------------------

    def kv_stats(self) -> Dict[str, Any]:
        """Host-side KV/batching occupancy snapshot (also the memplane
        gauge source via the registered provider)."""
        usable = self._alloc.num_usable
        free = self._alloc.num_free
        with self._cv:
            running = sum(1 for s in self._slots if s is not None)
            waiting = len(self._waiting)
            committed = self._committed_blocks
        bytes_per_block = 0
        try:
            k = self._pool["k"]
            bytes_per_block = int(
                k.dtype.itemsize * 2 * k.shape[0] * self.cfg.block_size
                * k.shape[2] * k.shape[3]
            )
        except Exception:
            pass
        return {
            "deployment": self.deployment,
            "block_size": self.cfg.block_size,
            "blocks_total": usable,
            "blocks_free": free,
            "blocks_committed": committed,
            "occupancy": 0.0 if not usable else 1.0 - free / usable,
            "running": running,
            "waiting": waiting,
            "bytes_per_block": bytes_per_block,
        }

    def _register_kv_provider(self) -> None:
        try:
            from ray_tpu._private import memplane

            memplane.register_kv_provider(self.deployment, self.kv_stats)
        except Exception:
            pass

    def _update_gauges(self) -> None:
        try:
            stats = self.kv_stats()
            m = _engine_metrics()
            tags = {"deployment": self.deployment}
            m["running"].set(float(stats["running"]), tags=tags)
            m["waiting"].set(float(stats["waiting"]), tags=tags)
            from ray_tpu._private import memplane

            memplane.record_kv_occupancy(stats)
        except Exception:
            pass

    # -- the loop -------------------------------------------------------

    def _has_active(self) -> bool:
        return any(s is not None for s in self._slots)

    def _loop(self) -> None:
        """One-step-pipelined scheduler: step k+1 is dispatched to the
        device BEFORE step k's tokens are emitted to consumers, so queue
        wakeups, gauge updates, and next-iteration admissions overlap
        device compute instead of extending the step critical path."""
        inflight = None
        while True:
            admits: List[tuple] = []
            with self._cv:
                while (
                    not self._stop
                    and not self._waiting
                    and not self._has_active()
                    and inflight is None
                ):
                    self._cv.wait(self.cfg.idle_poll_s)
                if self._stop:
                    return
                for i, slot in enumerate(self._slots):
                    if slot is None and self._waiting:
                        admits.append((i, *self._waiting.pop(0)))
            for slot_idx, req, stream in admits:
                self._do_prefill(slot_idx, req, stream)
            emissions: List[tuple] = []
            finishes: List[tuple] = []
            if inflight is not None:
                emissions, finishes = self._retire_step(inflight)
                inflight = None
            # finished slots detach (blocks freed) before the next
            # dispatch; their streams see the 'done' marker after their
            # final token below
            for slot_idx, _run, _reason in finishes:
                self._detach_slot(slot_idx)
            if self._has_active():
                inflight = self._dispatch_step()
            for stream, tok in emissions:
                stream._emit(tok)
            for _slot_idx, run, reason in finishes:
                run.req.out._finish(reason)
            if admits or emissions or finishes:
                self._update_gauges()

    # -- phases ---------------------------------------------------------

    def _bucket(self, n: int) -> int:
        b = max(int(self.cfg.prefill_bucket_min), 1)
        while b < n:
            b *= 2
        return b

    def _sample(self, logits_row, req: _Request, step: int) -> int:
        """One token from one sequence's logits; the PRNG stream is keyed
        by (seed, step) only, so sampling is batch-composition invariant."""
        import numpy as np

        if req.temperature and req.temperature > 0:
            tok = self._G.sample_token(
                logits_row,
                temperature=req.temperature,
                top_k=req.top_k,
                key=self._G.sequence_key(req.seed, step),
            )
            return int(np.asarray(tok))
        return int(np.asarray(logits_row).argmax())

    def _detach_slot(self, slot_idx: int) -> None:
        """Free a finished slot's KV blocks + admission reservation (the
        stream's 'done' marker is the caller's job, ordered after the
        final token emission)."""
        run = self._slots[slot_idx]
        run.table.release()  # blocks return to the pool immediately
        with self._cv:
            self._committed_blocks -= run.req.need_blocks
            self._slots[slot_idx] = None
            self._streams.pop(run.req.id, None)
            self._cv.notify_all()

    def _finish(self, slot_idx: int, reason: str) -> None:
        run = self._slots[slot_idx]
        self._detach_slot(slot_idx)
        run.req.out._finish(reason)

    def _fail_slot(self, slot_idx: int, error: BaseException) -> None:
        run = self._slots[slot_idx]
        run.table.release()
        with self._cv:
            self._committed_blocks -= run.req.need_blocks
            self._slots[slot_idx] = None
            self._streams.pop(run.req.id, None)
        run.req.out._fail(error)

    def _do_prefill(self, slot_idx: int, req: _Request, stream: TokenStream) -> None:
        import numpy as np
        import jax.numpy as jnp

        try:
            table = BlockTable(self._alloc)
            table.reserve(len(req.prompt))  # reserved at admission: cannot fail
            table.length = len(req.prompt)
            bucket = self._bucket(len(req.prompt))
            toks = np.zeros((1, bucket), np.int32)
            toks[0, : len(req.prompt)] = req.prompt
            bt = np.asarray(
                [table.as_list(self.cfg.max_blocks_per_seq)], np.int32
            )
            logits, self._pool = self._prefill(
                self.params,
                jnp.asarray(toks),
                jnp.asarray(bt),
                self._pool,
                jnp.int32(len(req.prompt)),
            )
            first = self._sample(logits[0], req, step=0)
        except BaseException as e:  # noqa: BLE001 — typed failure to the stream
            try:
                table.release()
            except Exception:
                pass
            with self._cv:
                self._committed_blocks -= req.need_blocks
                self._streams.pop(req.id, None)
            stream._fail(e)
            return
        try:
            _engine_metrics()["tokens"].inc(
                len(req.prompt),
                tags={"deployment": self.deployment, "phase": "prefill"},
            )
            _engine_metrics()["tokens"].inc(
                tags={"deployment": self.deployment, "phase": "decode"}
            )
        except Exception:
            pass
        run = _Running(req, table, first)
        self._slots[slot_idx] = run
        stream._emit(first)  # TTFT: admission -> first token
        if self._is_done(run, first):
            self._finish(slot_idx, self._done_reason(run, first))

    def _is_done(self, run: _Running, token: int) -> bool:
        return (
            run.generated >= run.req.max_new_tokens
            or (run.req.eos_token is not None and token == run.req.eos_token)
        )

    def _done_reason(self, run: _Running, token: int) -> str:
        if run.req.eos_token is not None and token == run.req.eos_token:
            return "stop"
        return "length"

    def _dispatch_step(self):
        """Enqueue one decode step on the device and return without
        waiting for it. A batch where every sequence decodes greedily
        uses the fused-argmax step (B ints cross back to the host, not
        B x vocab logits)."""
        import numpy as np
        import jax.numpy as jnp

        t0 = time.perf_counter()
        b = self.cfg.max_batch
        mb = self.cfg.max_blocks_per_seq
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        tables = np.zeros((b, mb), np.int32)
        active = np.zeros((b,), bool)
        live: List[int] = []
        fused = True
        for i, run in enumerate(self._slots):
            if run is None:
                continue
            # the input token lands at position `length`; growing the table
            # here can allocate a block — guaranteed by the admission
            # reservation to succeed
            pos = run.table.length
            run.table.append_token()
            tokens[i] = run.last_token
            positions[i] = pos
            tables[i] = run.table.as_list(mb)
            active[i] = True
            live.append(i)
            if run.req.temperature and run.req.temperature > 0:
                fused = False
        fn = self._decode_greedy if fused else self._decode
        try:
            out, self._pool = fn(
                self.params,
                jnp.asarray(tokens),
                jnp.asarray(positions),
                jnp.asarray(tables),
                self._pool,
                jnp.asarray(active),
            )
        except BaseException as e:  # noqa: BLE001
            for i in list(live):
                self._fail_slot(i, e)
            return None
        return (live, out, fused, t0)

    def _retire_step(self, inflight) -> tuple:
        """Block on the in-flight step's result and fold it into the run
        states. Returns ``(emissions, finishes)`` for the loop to deliver
        AFTER it dispatches the next step."""
        import numpy as np

        live, out, fused, t0 = inflight
        try:
            np_out = np.asarray(out)  # blocks until the device step lands
        except BaseException as e:  # noqa: BLE001
            for i in list(live):
                if self._slots[i] is not None:
                    self._fail_slot(i, e)
            return [], []
        emissions: List[tuple] = []
        finishes: List[tuple] = []
        for i in live:
            run = self._slots[i]
            if fused:
                tok = int(np_out[i])
            else:
                tok = self._sample(np_out[i], run.req, step=run.generated)
            run.generated += 1
            run.last_token = tok
            emissions.append((run.req.out, tok))
            if self._is_done(run, tok):
                finishes.append((i, run, self._done_reason(run, tok)))
        try:
            tags = {"deployment": self.deployment}
            m = _engine_metrics()
            m["tokens"].inc(len(emissions), tags={**tags, "phase": "decode"})
            m["step"].observe((time.perf_counter() - t0) * 1e3, tags=tags)
        except Exception:
            pass
        return emissions, finishes
