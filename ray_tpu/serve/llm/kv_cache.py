"""Paged KV cache bookkeeping: fixed-size blocks over a preallocated
device pool, handed out by a free-list allocator and mapped per sequence
by a block table.

The device arrays live in ``ray_tpu.models.generation`` (``init_paged_pool``
/ ``make_paged_fns``); this module is the host-side half: which pool block
belongs to which sequence. Fixed-size blocks make fragmentation structural
zero — any request for ``n <= num_free`` blocks always succeeds, there is
no external fragmentation to compact and no defrag pause on the decode
path. Block 0 is reserved as the null block (padding target for block
tables and masked writes) and is never allocated.

Parity: vLLM's ``BlockAllocator``/``BlockTable`` split (block_manager),
reduced to the synchronous single-device case the in-tree engine needs.
"""

from __future__ import annotations

import threading
from typing import List, Optional

__all__ = [
    "KVCacheExhausted",
    "BlockAllocator",
    "BlockTable",
    "NULL_BLOCK",
]

# block 0 of every pool is the write/padding sink; never owned by a sequence
NULL_BLOCK = 0


class KVCacheExhausted(Exception):
    """Typed allocator failure: the pool has fewer free blocks than the
    request needs. The engine's admission control makes this unreachable
    for admitted sequences (capacity is reserved up front); reaching it
    from ``allocate`` means an accounting bug, reaching it from admission
    becomes a ``DeploymentOverloadedError`` shed."""

    def __init__(self, requested: int, free: int):
        super().__init__(
            f"KV cache exhausted: requested {requested} block(s), "
            f"{free} free"
        )
        self.requested = requested
        self.free = free


class BlockAllocator:
    """LIFO free-list over blocks ``1..num_blocks-1`` (block 0 reserved).

    All-or-nothing: ``allocate(n)`` either returns ``n`` distinct blocks
    or raises ``KVCacheExhausted`` without side effects. LIFO reuse keeps
    recently-freed blocks hot (their pool slots are most likely still in
    cache on the host-staging path).
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._lock = threading.Lock()
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._held: set = set()

    @property
    def num_usable(self) -> int:
        """Total allocatable blocks (pool minus the reserved null block)."""
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        with self._lock:
            return len(self._free)

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` cache entries."""
        return -(-max(0, int(n_tokens)) // self.block_size)

    def allocate(self, n: int = 1) -> List[int]:
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        with self._lock:
            if n > len(self._free):
                raise KVCacheExhausted(n, len(self._free))
            out = [self._free.pop() for _ in range(n)]
            self._held.update(out)
            return out

    def free(self, blocks: List[int]) -> None:
        """Return blocks to the free list; double-free and foreign blocks
        are accounting bugs and raise rather than corrupting the pool."""
        with self._lock:
            for b in blocks:
                if b not in self._held:
                    raise ValueError(
                        f"freeing block {b} that is not allocated "
                        f"(double free or foreign block)"
                    )
                self._held.discard(b)
                self._free.append(b)


class BlockTable:
    """Per-sequence block list plus token length; grows one block at a
    time as decode crosses block boundaries."""

    __slots__ = ("allocator", "blocks", "length")

    def __init__(self, allocator: BlockAllocator, n_tokens: int = 0):
        self.allocator = allocator
        self.blocks: List[int] = []
        self.length = 0
        if n_tokens:
            self.reserve(n_tokens)

    def reserve(self, n_tokens: int) -> None:
        """Grow the table to cover ``n_tokens`` total positions."""
        need = self.allocator.blocks_for_tokens(n_tokens) - len(self.blocks)
        if need > 0:
            self.blocks.extend(self.allocator.allocate(need))

    def append_token(self) -> int:
        """Account one more cache entry, allocating a block on boundary
        crossings; returns the new length."""
        self.reserve(self.length + 1)
        self.length += 1
        return self.length

    def release(self) -> None:
        """Free every owned block (idempotent)."""
        if self.blocks:
            self.allocator.free(self.blocks)
            self.blocks = []

    def as_list(self, max_blocks: int) -> List[int]:
        """Dense table padded with the null block to ``max_blocks``."""
        if len(self.blocks) > max_blocks:
            raise ValueError(
                f"sequence spans {len(self.blocks)} blocks > "
                f"max_blocks_per_seq {max_blocks}"
            )
        return self.blocks + [NULL_BLOCK] * (max_blocks - len(self.blocks))
