"""LLM serving plane: paged KV cache + continuous batching on serve.

Import lazily (``from ray_tpu.serve import llm``) — this package pulls in
jax via the model family, which plain serve users should not pay for.
"""

from ray_tpu.serve.llm.deployment import TINY_MODEL, LLMServer, llm_deployment
from ray_tpu.serve.llm.engine import EngineConfig, InferenceEngine, TokenStream
from ray_tpu.serve.llm.kv_cache import (
    NULL_BLOCK,
    BlockAllocator,
    BlockTable,
    KVCacheExhausted,
)

__all__ = [
    "BlockAllocator",
    "BlockTable",
    "EngineConfig",
    "InferenceEngine",
    "KVCacheExhausted",
    "LLMServer",
    "NULL_BLOCK",
    "TINY_MODEL",
    "TokenStream",
    "llm_deployment",
]
