"""The LLM deployment: one :class:`InferenceEngine` per serve replica.

``LLMServer.generate`` is a generator method, so it rides every existing
streaming surface unchanged: handle ``.options(stream=True)`` iteration,
the proxy's SSE/chunked path, and websockets — with TTFT landing in the
replica's stream spans and ``ray_tpu_serve_ttft_ms`` exactly like any
other streaming deployment. Engine-side KV-exhaustion sheds raise
``DeploymentOverloadedError`` before the first token, which the serve
plane already maps to HTTP 503 + Retry-After.

Model weights are initialised from a seed inside the replica (this repo
has no checkpoint loader); pass ``params_loader`` for real weights.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional, Sequence, Union

from ray_tpu.serve.llm.engine import EngineConfig, InferenceEngine

__all__ = ["LLMServer", "llm_deployment", "TINY_MODEL"]

# small-but-real geometry (GQA + swiglu exercised) usable on the CPU
# backend: tests, benches and docs all deploy this by default
TINY_MODEL: Dict[str, Any] = {
    "vocab_size": 512,
    "d_model": 64,
    "n_layers": 2,
    "n_heads": 4,
    "n_kv_heads": 2,
    "d_ff": 128,
    "max_seq_len": 256,
    "dtype": "float32",
}


def _resolve_model_cfg(model_cfg):
    from ray_tpu.models.transformer import TransformerConfig

    if model_cfg is None:
        model_cfg = TINY_MODEL
    if isinstance(model_cfg, TransformerConfig):
        return model_cfg
    import jax.numpy as jnp

    cfg = dict(model_cfg)
    if isinstance(cfg.get("dtype"), str):
        cfg["dtype"] = jnp.dtype(cfg["dtype"]).type
    return TransformerConfig(**cfg)


def _resolve_engine_cfg(engine_cfg):
    if engine_cfg is None:
        return EngineConfig()
    if isinstance(engine_cfg, EngineConfig):
        return engine_cfg
    return EngineConfig(**dict(engine_cfg))


class LLMServer:
    """Serve deployment class wrapping the continuous-batching engine.

    Configs arrive as plain dicts (cloudpickle-friendly across the actor
    boundary) or as the dataclasses themselves.
    """

    def __init__(
        self,
        model_cfg: Optional[Union[Dict, Any]] = None,
        engine_cfg: Optional[Union[Dict, EngineConfig]] = None,
        *,
        weight_seed: int = 0,
        deployment: str = "llm",
        params_loader: Optional[Callable[[Any], Any]] = None,
    ):
        import jax

        from ray_tpu.models.transformer import init_params

        cfg = _resolve_model_cfg(model_cfg)
        if params_loader is not None:
            params = params_loader(cfg)
        else:
            params = init_params(jax.random.PRNGKey(int(weight_seed)), cfg)
        self._engine = InferenceEngine(
            params, cfg, _resolve_engine_cfg(engine_cfg), deployment=deployment
        )

    def generate(
        self,
        prompt: Sequence[int],
        max_new_tokens: int = 16,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int = 0,
        eos_token: Optional[int] = None,
    ) -> Iterator[int]:
        """Stream generated token ids. Admission (and therefore any
        ``DeploymentOverloadedError`` shed) happens eagerly at call time,
        before the first yield, so sheds surface as pre-first-token
        failures on every transport."""
        stream = self._engine.submit(
            prompt,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            top_k=top_k,
            seed=seed,
            eos_token=eos_token,
        )

        def _iter():
            for tok in stream:
                yield int(tok)

        return _iter()

    def __call__(
        self, prompt, max_new_tokens: int = 16, **kw
    ) -> list:
        """Unary convenience: full completion as a token list. Accepts
        either a token sequence or the HTTP-proxy JSON convention
        (``{"prompt": [...], "max_new_tokens": ..., ...}`` as one arg)."""
        if isinstance(prompt, dict):
            payload = dict(prompt)
            tokens = payload.pop("prompt")
            max_new_tokens = payload.pop("max_new_tokens", max_new_tokens)
            kw = {**payload, **kw}
            prompt = tokens
        return list(self.generate(prompt, max_new_tokens, **kw))

    def kv_stats(self) -> Dict[str, Any]:
        return self._engine.kv_stats()

    def check_health(self) -> bool:
        if self._engine._thread is None or not self._engine._thread.is_alive():
            raise RuntimeError("inference engine loop is not running")
        return True

    def __del__(self):
        try:
            self._engine.shutdown(timeout_s=1.0)
        except Exception:
            pass


def llm_deployment(
    model_cfg: Optional[Dict] = None,
    engine_cfg: Optional[Dict] = None,
    *,
    deployment_name: str = "llm",
    **serve_options,
):
    """Bound LLM application: ``serve.run(llm_deployment(...))``.

    ``serve_options`` pass straight through to ``@serve.deployment``
    (num_replicas, max_ongoing_requests, autoscaling_config, ...).
    ``max_ongoing_requests`` defaults to the engine's admission width
    (decode slots + waiting bound) so the replica gate and the KV-aware
    admission agree about capacity.
    """
    from ray_tpu import serve

    ecfg = _resolve_engine_cfg(engine_cfg)
    serve_options.setdefault("name", deployment_name)
    serve_options.setdefault(
        "max_ongoing_requests", ecfg.max_batch + ecfg.max_waiting
    )
    dep = serve.deployment(LLMServer, **serve_options)
    return dep.bind(
        model_cfg, engine_cfg, deployment=deployment_name
    )
