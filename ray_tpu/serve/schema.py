"""Serve declarative config: build an app to a dict/YAML, deploy from it.

Parity: ``python/ray/serve/schema.py`` (ServeDeploySchema /
ServeApplicationSchema) and the ``serve build`` / ``serve deploy`` CLI flow —
an application is declared as an ``import_path`` (``module:bound_app``) plus
per-deployment overrides; deploying imports the bound graph, applies the
overrides, and hands it to ``serve.run``.
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional

_DEPLOYMENT_OVERRIDE_KEYS = (
    "num_replicas",
    "max_ongoing_requests",
    "ray_actor_options",
    "autoscaling_config",
    "health_check_period_s",
    "user_config",
    # resilience knobs (see Deployment docstring)
    "graceful_shutdown_timeout_s",
    "request_timeout_s",
    "request_retries",
    "shed_queue_factor",
    "shed_retry_after_s",
)


def build(app, *, import_path: str, name: str = "default",
          route_prefix: Optional[str] = None) -> Dict[str, Any]:
    """Produce the declarative config for a bound application (parity:
    ``serve build``). ``import_path`` must be "module:attr" pointing at the
    bound app — deploy re-imports it, so a config without one is undeployable."""
    from ray_tpu.serve.api import Application, _flatten_graph

    if not isinstance(app, Application):
        raise TypeError("serve.build expects a bound deployment (use .bind())")
    if not import_path or ":" not in import_path:
        raise ValueError(
            f"import_path must be 'module:attribute', got {import_path!r}"
        )
    specs, _ = _flatten_graph(app)
    deployments: List[Dict[str, Any]] = []
    for spec in specs:
        d: Dict[str, Any] = {"name": spec["name"]}
        d["num_replicas"] = spec["num_replicas"]
        d["max_ongoing_requests"] = spec["max_ongoing_requests"]
        if spec.get("ray_actor_options"):
            d["ray_actor_options"] = spec["ray_actor_options"]
        if spec.get("autoscaling_config"):
            d["autoscaling_config"] = spec["autoscaling_config"]
        if spec.get("user_config") is not None:
            d["user_config"] = spec["user_config"]
        for knob, default in (
            ("graceful_shutdown_timeout_s", 20.0),
            ("request_timeout_s", 120.0),
            ("request_retries", 3),
            ("shed_queue_factor", 6.0),
            ("shed_retry_after_s", 1.0),
            ("health_check_period_s", 5.0),
        ):
            if spec.get(knob) is not None and spec[knob] != default:
                d[knob] = spec[knob]
        deployments.append(d)
    app_schema: Dict[str, Any] = {
        "name": name,
        "import_path": import_path,
        "deployments": deployments,
    }
    if route_prefix is not None:
        app_schema["route_prefix"] = route_prefix
    return {"applications": [app_schema]}


def _import_bound_app(import_path: str):
    if ":" not in import_path:
        raise ValueError(
            f"import_path must be 'module:attribute', got {import_path!r}"
        )
    module_name, attr = import_path.split(":", 1)
    module = importlib.import_module(module_name)
    app = module
    for part in attr.split("."):
        app = getattr(app, part)
    return app


def _apply_overrides(app, overrides: Dict[str, Dict[str, Any]]):
    """Rebuild the bound graph with per-deployment option overrides."""
    from ray_tpu.serve.api import Application

    rebuilt: Dict[int, Application] = {}

    def visit(node):
        if not isinstance(node, Application):
            return node
        if id(node) in rebuilt:
            return rebuilt[id(node)]
        args = tuple(visit(a) for a in node.args)
        kwargs = {k: visit(v) for k, v in node.kwargs.items()}
        dep = node.deployment
        ov = overrides.get(dep.name)
        if ov:
            dep = dep.options(**{k: v for k, v in ov.items()
                                 if k in _DEPLOYMENT_OVERRIDE_KEYS})
        new = Application(dep, args, kwargs)
        rebuilt[id(node)] = new
        return new

    return visit(app)


def deploy_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Deploy every application in a config dict (parity: ``serve deploy`` /
    REST ``PUT /api/serve/applications``). Returns {app_name: handle}."""
    from ray_tpu.serve import api as serve_api

    handles = {}
    for app_schema in config.get("applications", []):
        name = app_schema.get("name", "default")
        import_path = app_schema["import_path"]
        app = _import_bound_app(import_path)
        overrides = {
            d["name"]: d for d in app_schema.get("deployments", [])
        }
        app = _apply_overrides(app, overrides)
        handles[name] = serve_api.run(
            app, name=name, route_prefix=app_schema.get("route_prefix")
        )
    return handles


def deploy_config_file(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as fh:
        config = yaml.safe_load(fh)
    return deploy_config(config)


def dump_config(config: Dict[str, Any], path: Optional[str] = None) -> str:
    import yaml

    text = yaml.safe_dump(config, sort_keys=False)
    if path:
        with open(path, "w") as fh:
            fh.write(text)
    return text
