"""DeploymentHandle: the data-plane RPC handle between callers and replicas.

Parity: ``python/ray/serve/handle.py`` + the power-of-two-choices replica
scheduler (``replica_scheduler/pow_2_scheduler.py:49``): pick two random
replicas, send to the one with fewer requests outstanding *from this handle*.
Extensions matching the reference: streaming responses
(``handle.options(stream=True)``), model-multiplex-aware routing
(``options(multiplexed_model_id=...)`` prefers replicas that already hold
the model), and periodic replica-list refresh so autoscaling is visible to
live handles.

Resilience plane (parity: the retry/backpressure semantics of the replica
scheduler + ``proxy_request_response``): dead or DRAINING replicas are
excluded from pow-2 picks the moment an error identifies them; requests the
scheduler proves never started executing (``ActorDiedError.task_started is
False``, or a drain rejection) fail over transparently to another replica
under a bounded backoff budget; torn work surfaces as a typed
:class:`~ray_tpu.serve.exceptions.ReplicaDiedError`. Admission control sheds
load with :class:`~ray_tpu.serve.exceptions.DeploymentOverloadedError` once
queued work exceeds ``replicas x max_ongoing_requests x shed_queue_factor``,
with a half-open probe per ``shed_retry_after_s`` window when the trigger is
(possibly stale) controller-probed depth rather than live local load.
"""

from __future__ import annotations

import random
import threading
import time
import warnings
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.exceptions import ActorDiedError, GetTimeoutError
from ray_tpu.serve.exceptions import (
    DeploymentOverloadedError,
    ReplicaDiedError,
    ReplicaDrainingError,
    RequestTimeoutError,
)

_REFRESH_PERIOD_S = 2.0
_EXCLUDE_TTL_S = 30.0
_RETRY_BACKOFF_S = 0.05
_RETRY_BACKOFF_MAX_S = 1.0
_SHED_EVENT_PERIOD_S = 5.0

# per-deployment knobs a handle needs; refreshed from the controller's
# handle-info, seeded from Deployment at construction (see Deployment
# docstring for what each knob does)
_DEFAULT_CFG = {
    "max_ongoing": 8,
    "shed_queue_factor": 6.0,
    "shed_retry_after_s": 1.0,
    "request_timeout_s": 120.0,
    "request_retries": 3,
    "graceful_shutdown_timeout_s": 20.0,
    # autoscaling max_replicas (None when not autoscaled): admission
    # capacity is computed against the deployment's MAX size — queued work
    # is the scale-up signal, shedding it would starve the autoscaler
    "max_replicas": None,
}

_warned_option_keys: set = set()

# handle-side telemetry (driver or proxy process); lazy singletons like the
# replica metrics — records are local dict updates batched by telemetry
_metrics: dict = {}


def _handle_metrics() -> dict:
    if not _metrics:
        from ray_tpu.util.metrics import Counter

        _metrics["retries"] = Counter(
            "ray_tpu_serve_retries_total",
            "transparent replica-failover retries of unstarted requests",
            tag_keys=("deployment",),
        )
        _metrics["shed"] = Counter(
            "ray_tpu_serve_shed_total",
            "requests shed by deployment admission control",
            tag_keys=("deployment",),
        )
    return _metrics


def _record_counter(name: str, deployment: str) -> None:
    try:
        _handle_metrics()[name].inc(tags={"deployment": deployment})
    except Exception:
        pass  # metrics never fail a request


def _trace_event(name: str, **extra) -> None:
    """Instant span under the active trace context (retry/shed decisions —
    the handle's routing story inside ray_tpu.trace output). No-op when
    untraced; never fails a request."""
    try:
        from ray_tpu.util import tracing
        from ray_tpu._private import telemetry

        ctx = tracing.get_current_context()
        if ctx is None:
            return
        now = time.time()
        telemetry.record_span(
            {
                "event": name,
                "start": now,
                "end": now,
                "duration_ms": 0.0,
                "pid": __import__("os").getpid(),
                "extra": {
                    **extra,
                    "trace_id": ctx.trace_id,
                    "span_id": tracing._new_id(8),
                    "parent_id": ctx.span_id,
                },
            }
        )
    except Exception:
        pass


class DeploymentResponse:
    """Future for one deployment call (parity: ``DeploymentResponse``).

    ``result()`` transparently fails the call over to another replica when
    the scheduler proves the request never started executing on a dead or
    draining replica; torn work raises ``ReplicaDiedError``.
    """

    def __init__(self, ref: ray_tpu.ObjectRef, on_done=None, call=None):
        self._ref = ref
        self._on_done = on_done
        self._settled = False
        # (handle, method, args, kwargs, replica_id): retained for failover
        # re-dispatch; None for bare refs (back-compat constructions)
        self._call = call
        self._attempts = 0
        # the request's trace context: failover re-dispatches re-activate it
        # so retried attempts land in the SAME trace
        self._trace_ctx = None

    def result(self, timeout_s: Optional[float] = None) -> Any:
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        while True:
            remaining = (
                None if deadline is None else max(0.0, deadline - time.monotonic())
            )
            try:
                value = ray_tpu.get(self._ref, timeout=remaining)
            except BaseException as e:  # noqa: BLE001
                if self._call is None or _classify_failure(e) is None:
                    self._settle()
                    raise
                try:
                    self._redispatch(e)
                except BaseException:
                    self._settle()
                    raise
                continue
            self._settle()
            return value

    def _redispatch(self, error: BaseException) -> None:
        """Fail over to another replica (or raise ReplicaDiedError)."""
        from ray_tpu.util import tracing

        handle, method, args, kwargs, rid = self._call
        with tracing.scope(self._trace_ctx):
            new_ref, new_rid, new_done = handle._failover(
                method, args, kwargs, rid, error, self._attempts
            )
        self._attempts += 1
        # settle the failed dispatch's outstanding slot, then track the new
        if self._on_done:
            try:
                self._on_done()
            except Exception:
                pass
        self._ref = new_ref
        self._on_done = new_done
        self._call = (handle, method, args, kwargs, new_rid)

    def _settle(self):
        if not self._settled:
            self._settled = True
            self._call = None  # release retained args once the call settles
            if self._on_done:
                self._on_done()

    def __del__(self):
        # fire-and-forget callers never call result(); settle on GC so the
        # replica's outstanding counter doesn't inflate forever
        try:
            self._settle()
        except Exception:
            pass

    def _to_object_ref(self) -> ray_tpu.ObjectRef:
        return self._ref


class DeploymentResponseGenerator:
    """Streaming response: iterate per-item results (parity:
    ``DeploymentResponseGenerator``).

    A stream whose replica dies before the first item failed over to
    another replica (nothing was delivered, nothing is torn); once items
    have flowed, replica death surfaces as ``ReplicaDiedError(started=True)``
    — the caller owns dedup/resume semantics for partially-consumed streams.
    Per-item waits are bounded by the handle's ``stream_item_timeout_s``
    (``options()``), raising a typed ``RequestTimeoutError``.
    """

    def __init__(self, gen=None, on_done=None, *, handle=None, method=None,
                 args=None, kwargs=None, trace_ctx=None):
        # legacy positional (gen, on_done) construction still works for
        # callers that pre-dispatched; handle-driven construction enables
        # failover re-dispatch
        self._gen = gen
        self._on_done = on_done
        self._settled = False
        self._handle = handle
        self._method = method
        self._args = args
        self._kwargs = kwargs
        # request trace context: every (re-)dispatch activates it so the
        # stream's attempts all land in one trace
        self._trace_ctx = trace_ctx

    def __iter__(self):
        if self._handle is None:
            yield from self._iter_legacy()
            return
        from ray_tpu.util import tracing

        handle = self._handle
        item_timeout = handle._stream_item_timeout_s
        attempts = 0
        while True:
            with tracing.scope(self._trace_ctx):
                gen, rid, done = handle._dispatch(
                    self._method, self._args, self._kwargs, streaming=True
                )
            got_any = False
            try:
                try:
                    next_ref = getattr(gen, "next_ref", None)
                    while True:
                        try:
                            # bounded per-item wait (typed timeout) — the
                            # producer committing nothing for item_timeout
                            # must not park the consumer forever
                            ref = (
                                next_ref(item_timeout)
                                if next_ref is not None
                                else next(gen)
                            )
                        except StopIteration:
                            return
                        except GetTimeoutError as te:
                            raise RequestTimeoutError(
                                handle.deployment_name,
                                self._method,
                                item_timeout,
                            ) from te
                        try:
                            item = ray_tpu.get(ref, timeout=item_timeout)
                        except GetTimeoutError as te:
                            if isinstance(te, RequestTimeoutError):
                                raise
                            raise RequestTimeoutError(
                                handle.deployment_name,
                                self._method,
                                item_timeout,
                            ) from te
                        got_any = True
                        yield item
                finally:
                    done()
            except GeneratorExit:
                raise  # consumer stopped early
            except RequestTimeoutError:
                raise
            except BaseException as e:  # noqa: BLE001
                retriable = _classify_failure(e)
                if retriable is None:
                    raise  # application error: not a replica-death failure
                handle._note_replica_gone(rid)
                if got_any or not retriable:
                    raise ReplicaDiedError(
                        deployment=handle.deployment_name,
                        app=handle.app_name,
                        method=self._method,
                        replica_id=rid,
                        started=True if got_any else _started_of(e),
                        reason=str(e),
                    ) from e
                if attempts >= handle._retry_budget(e):
                    raise ReplicaDiedError(
                        deployment=handle.deployment_name,
                        app=handle.app_name,
                        method=self._method,
                        replica_id=rid,
                        started=False,
                        reason=f"retry budget exhausted: {e}",
                    ) from e
                attempts += 1
                handle._backoff_and_refresh(attempts)
                _record_counter("retries", handle.deployment_name)
                from ray_tpu.util import tracing as _tracing

                with _tracing.scope(self._trace_ctx):
                    _trace_event(
                        "serve:retry",
                        deployment=handle.deployment_name,
                        method=self._method,
                        failed_replica=rid,
                        attempt=attempts,
                        reason=type(e).__name__,
                    )

    def _iter_legacy(self):
        try:
            for ref in self._gen:
                yield ray_tpu.get(ref, timeout=300)
        finally:
            if not self._settled:
                self._settled = True
                if self._on_done:
                    self._on_done()


def _classify_failure(e: BaseException) -> Optional[bool]:
    """None: not a replica-death/drain failure (application error — do not
    touch). True: provably unstarted, safe to retry. False: replica died
    under (possibly) started work."""
    if isinstance(e, ReplicaDrainingError):
        return True
    if isinstance(e, ActorDiedError):
        return getattr(e, "task_started", None) is False
    return None


def _started_of(e: BaseException) -> Optional[bool]:
    if isinstance(e, ActorDiedError):
        return getattr(e, "task_started", None)
    return None


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._call(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(
        self,
        deployment_name: str,
        app_name: str,
        replicas: List[Any],
        stream: bool = False,
        multiplexed_model_id: str = "",
        max_retries: Optional[int] = None,
        stream_item_timeout_s: float = 300.0,
        shed_enabled: bool = True,
        config: Optional[dict] = None,
    ):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._replicas = list(replicas)
        self._outstanding: Dict[int, int] = {i: 0 for i in range(len(replicas))}
        # controller-probed queue depths by replica id (staleness <= the
        # reconcile period): lets pow-2 see load from OTHER handles too,
        # parity with the replica probes of pow_2_scheduler.py:49
        self._probed_depths: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._stream = stream
        self._model_id = multiplexed_model_id
        # model id -> replica index this handle last routed it to
        self._model_affinity: Dict[str, int] = {}
        self._last_refresh = time.monotonic()
        # resilience state
        self._cfg = dict(_DEFAULT_CFG)
        if config:
            self._cfg.update({k: v for k, v in config.items() if v is not None})
        self._max_retries = max_retries
        self._stream_item_timeout_s = stream_item_timeout_s
        self._shed_enabled = shed_enabled
        # replica id hex -> monotonic ts: dead/draining replicas excluded
        # from picks until the controller's handle-info drops them
        self._excluded: Dict[str, float] = {}
        self._health = "HEALTHY"
        self._next_probe_at = 0.0  # half-open probe gate while shedding
        self._last_shed_event = 0.0
        self._retry_count = 0  # introspection/tests: failover retries taken
        self._shed_count = 0

    # -- replica-set maintenance ------------------------------------------

    def _update_replicas(self, replicas: List[Any]):
        with self._lock:
            self._replicas = list(replicas)
            self._outstanding = {i: 0 for i in range(len(replicas))}
            self._model_affinity.clear()
            live = {r._actor_id.hex() for r in self._replicas}
            for rid in [x for x in self._excluded if x not in live]:
                del self._excluded[rid]

    def _maybe_refresh(self, force: bool = False):
        """Pick up autoscaling/failover changes: re-fetch the replica list
        from the controller every couple of seconds (immediately when a
        failover forces it)."""
        now = time.monotonic()
        if not force and now - self._last_refresh < _REFRESH_PERIOD_S:
            return
        self._last_refresh = now
        try:
            from ray_tpu.serve.api import _CONTROLLER_NAME

            controller = ray_tpu.get_actor(_CONTROLLER_NAME)
            info = ray_tpu.get(
                controller.get_handle_info.remote(self.app_name, self.deployment_name),
                timeout=10,
            )
            if info is not None:
                new_replicas = info["replicas"]
                new_ids = [r._actor_id for r in new_replicas]
                cur_ids = [r._actor_id for r in self._replicas]
                if new_ids != cur_ids:
                    self._update_replicas(new_replicas)
                with self._lock:
                    if info.get("depths"):
                        self._probed_depths = dict(info["depths"])
                    cfg = info.get("config")
                    if cfg:
                        self._cfg.update(
                            {k: v for k, v in cfg.items() if v is not None}
                        )
                    self._health = info.get("health", self._health)
        except Exception:
            pass

    def _note_replica_gone(self, rid: str) -> None:
        """Exclude a dead/draining replica from picks and force the next
        call to refresh from the controller."""
        now = time.monotonic()
        with self._lock:
            self._excluded[rid] = now
            for old in [
                r for r, ts in self._excluded.items() if now - ts > _EXCLUDE_TTL_S
            ]:
                del self._excluded[old]
        self._last_refresh = 0.0

    # -- routing -----------------------------------------------------------

    def _pick(self, model_id: str) -> int:
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError(
                    f"deployment {self.deployment_name} has no replicas"
                )
            eligible = [
                k
                for k in range(n)
                if self._replicas[k]._actor_id.hex() not in self._excluded
            ]
            if not eligible:
                # every known replica is excluded (e.g. mass churn between
                # refreshes): fall back to the full set rather than brick —
                # the bounded failover budget still caps the damage
                eligible = list(range(n))
            # multiplex-aware: stick with the replica that already loaded
            # this model unless it is heavily loaded (pow-2 fallback)
            if model_id:
                idx = self._model_affinity.get(model_id)
                if (
                    idx is not None
                    and idx in eligible
                    and self._outstanding.get(idx, 0) < 8
                ):
                    return idx
            if len(eligible) == 1:
                idx = eligible[0]
            else:
                i, j = random.sample(eligible, 2)

                def score(k: int) -> int:
                    # local in-flight plus the controller-probed global queue
                    # depth (load from other handles/proxies)
                    rid = self._replicas[k]._actor_id.hex()
                    return self._outstanding.get(k, 0) + self._probed_depths.get(
                        rid, 0
                    )

                idx = i if score(i) <= score(j) else j
            if model_id:
                self._model_affinity[model_id] = idx
            return idx

    # -- admission control (load shedding) --------------------------------

    def _check_admission(self, extra_load: int = 0) -> None:
        """Shed when queued work exceeds the deployment's bound; raises
        DeploymentOverloadedError (the proxy maps it to 503+Retry-After)."""
        if not self._shed_enabled:
            return
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                return
            max_replicas = self._cfg.get("max_replicas")
            n_eff = max(n, int(max_replicas)) if max_replicas else n
            cap = max(
                1,
                int(
                    n_eff
                    * float(self._cfg["max_ongoing"])
                    * float(self._cfg["shed_queue_factor"])
                ),
            )
            local = sum(self._outstanding.values()) + extra_load
            probed = sum(self._probed_depths.values())
            load = max(local, probed)
            if load < cap:
                return
            retry_after = float(self._cfg["shed_retry_after_s"])
            now = time.monotonic()
            if local < cap and now >= self._next_probe_at:
                # trigger is controller-probed (possibly stale) depth, not
                # live local load: half-open — admit one probe request per
                # retry_after window so a freed deployment closes the
                # breaker without waiting for the next depth refresh
                self._next_probe_at = now + retry_after
                return
            self._shed_count += 1
            emit_event = now - self._last_shed_event > _SHED_EVENT_PERIOD_S
            if emit_event:
                self._last_shed_event = now
        _record_counter("shed", self.deployment_name)
        _trace_event(
            "serve:shed",
            deployment=self.deployment_name,
            load=load,
            capacity=cap,
        )
        if emit_event:
            try:
                from ray_tpu._private.telemetry import record_cluster_event

                record_cluster_event(
                    "SERVE_SHED",
                    f"deployment {self.deployment_name} shedding load "
                    f"(load {load} >= capacity {cap})",
                    severity="WARNING",
                    source="SERVE",
                    deployment=self.deployment_name,
                    app=self.app_name,
                    load=load,
                    capacity=cap,
                )
            except Exception:
                pass
        raise DeploymentOverloadedError(
            self.deployment_name, retry_after, load, cap
        )

    # -- dispatch + failover ----------------------------------------------

    def _dispatch(self, method: str, args, kwargs, streaming: bool = False):
        """One dispatch attempt; returns (ref_or_gen, replica_id, done)."""
        idx = self._pick(self._model_id)
        with self._lock:
            # bind the generation's counter dict: a replica-list refresh swaps
            # it out, and late done() callbacks must decrement the dict they
            # incremented (not drive the fresh one negative)
            out_map = self._outstanding
            out_map[idx] = out_map.get(idx, 0) + 1
            replica = self._replicas[idx]

        def done():
            with self._lock:
                if idx in out_map:
                    out_map[idx] -= 1

        rid = replica._actor_id.hex()
        if streaming:
            gen = replica.handle_request_streaming.options(
                num_returns="streaming"
            ).remote(method, list(args), dict(kwargs), self._model_id)
            return gen, rid, done
        ref = replica.handle_request.remote(
            method, list(args), dict(kwargs), self._model_id
        )
        return ref, rid, done

    def _retry_budget(self, error: Optional[BaseException] = None) -> int:
        base = (
            int(self._max_retries)
            if self._max_retries is not None
            else int(self._cfg["request_retries"])
        )
        if isinstance(error, ReplicaDrainingError):
            # drain rejections are provably unstarted and redeploy storms
            # are transient (every old replica can reject until the handle's
            # forced refresh lands on a slow host): extra headroom is safe
            return base + 4
        return base

    def _backoff_and_refresh(self, attempt: int) -> None:
        time.sleep(min(_RETRY_BACKOFF_S * (2 ** max(0, attempt - 1)),
                       _RETRY_BACKOFF_MAX_S))
        self._maybe_refresh(force=True)

    def _failover(self, method: str, args, kwargs, rid: str,
                  error: BaseException, attempts_used: int):
        """Handle a dead/draining-replica failure of one unary dispatch:
        returns a replacement (ref, replica_id, done) or raises the typed
        terminal error. Only called for failures _classify_failure
        recognized."""
        retriable = _classify_failure(error)
        self._note_replica_gone(rid)
        if not retriable:
            raise ReplicaDiedError(
                deployment=self.deployment_name,
                app=self.app_name,
                method=method,
                replica_id=rid,
                started=_started_of(error),
                reason=str(error),
            ) from error
        if attempts_used >= self._retry_budget(error):
            raise ReplicaDiedError(
                deployment=self.deployment_name,
                app=self.app_name,
                method=method,
                replica_id=rid,
                started=False,
                reason=f"retry budget exhausted: {error}",
            ) from error
        self._backoff_and_refresh(attempts_used + 1)
        with self._lock:
            self._retry_count += 1
        _record_counter("retries", self.deployment_name)
        _trace_event(
            "serve:retry",
            deployment=self.deployment_name,
            method=method,
            failed_replica=rid,
            attempt=attempts_used + 1,
            reason=type(error).__name__,
        )
        return self._dispatch(method, args, kwargs)

    def _call(self, method: str, args, kwargs):
        from ray_tpu.util import tracing

        self._maybe_refresh()
        # tracing entry point: a driver-side serve call with no active
        # context roots a fresh trace (proxy requests arrive with one)
        ctx = tracing.get_current_context()
        if ctx is None and tracing.tracing_enabled():
            ctx = tracing.new_root()
        with tracing.scope(ctx):
            self._check_admission()
            if self._stream:
                return DeploymentResponseGenerator(
                    handle=self, method=method, args=args, kwargs=kwargs,
                    trace_ctx=ctx,
                )
            from ray_tpu._private.profiling import traced_section

            with traced_section(
                f"serve:handle:{self.deployment_name}.{method}",
                {"deployment": self.deployment_name, "app": self.app_name},
            ) as sx:
                ref, rid, done = self._dispatch(method, args, kwargs)
                sx["replica_id"] = rid
        resp = DeploymentResponse(
            ref, on_done=done, call=(self, method, args, kwargs, rid)
        )
        resp._trace_ctx = ctx
        return resp

    def remote(self, *args, **kwargs):
        return self._call("__call__", args, kwargs)

    def options(
        self,
        *,
        stream: Optional[bool] = None,
        multiplexed_model_id: Optional[str] = None,
        max_retries: Optional[int] = None,
        stream_item_timeout_s: Optional[float] = None,
        shed_enabled: Optional[bool] = None,
        **unknown,
    ) -> "DeploymentHandle":
        for key in unknown:
            # warn once per unknown key process-wide (silently dropping a
            # typo'd kwarg hid real misconfiguration)
            if key not in _warned_option_keys:
                _warned_option_keys.add(key)
                warnings.warn(
                    f"DeploymentHandle.options() ignoring unknown option "
                    f"{key!r}",
                    stacklevel=2,
                )
        return DeploymentHandle(
            self.deployment_name,
            self.app_name,
            self._replicas,
            stream=self._stream if stream is None else stream,
            multiplexed_model_id=(
                self._model_id if multiplexed_model_id is None else multiplexed_model_id
            ),
            max_retries=self._max_retries if max_retries is None else max_retries,
            stream_item_timeout_s=(
                self._stream_item_timeout_s
                if stream_item_timeout_s is None
                else stream_item_timeout_s
            ),
            shed_enabled=self._shed_enabled if shed_enabled is None else shed_enabled,
            config=dict(self._cfg),
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def __reduce__(self):
        return (
            _rebuild_handle,
            (
                self.deployment_name,
                self.app_name,
                self._replicas,
                self._stream,
                self._model_id,
                self._max_retries,
                self._stream_item_timeout_s,
                self._shed_enabled,
                dict(self._cfg),
            ),
        )


def _rebuild_handle(deployment_name, app_name, replicas, stream, model_id,
                    max_retries, stream_item_timeout_s, shed_enabled, cfg):
    return DeploymentHandle(
        deployment_name,
        app_name,
        replicas,
        stream=stream,
        multiplexed_model_id=model_id,
        max_retries=max_retries,
        stream_item_timeout_s=stream_item_timeout_s,
        shed_enabled=shed_enabled,
        config=cfg,
    )
