"""DeploymentHandle: the data-plane RPC handle between callers and replicas.

Parity: ``python/ray/serve/handle.py`` + the power-of-two-choices replica
scheduler (``replica_scheduler/pow_2_scheduler.py:49``): pick two random
replicas, send to the one with fewer requests outstanding *from this handle*.
Extensions matching the reference: streaming responses
(``handle.options(stream=True)``), model-multiplex-aware routing
(``options(multiplexed_model_id=...)`` prefers replicas that already hold
the model), and periodic replica-list refresh so autoscaling is visible to
live handles.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu

_REFRESH_PERIOD_S = 2.0


class DeploymentResponse:
    """Future for one deployment call (parity: ``DeploymentResponse``)."""

    def __init__(self, ref: ray_tpu.ObjectRef, on_done=None):
        self._ref = ref
        self._on_done = on_done
        self._settled = False

    def result(self, timeout_s: Optional[float] = None) -> Any:
        try:
            value = ray_tpu.get(self._ref, timeout=timeout_s)
        finally:
            self._settle()
        return value

    def _settle(self):
        if not self._settled:
            self._settled = True
            if self._on_done:
                self._on_done()

    def __del__(self):
        # fire-and-forget callers never call result(); settle on GC so the
        # replica's outstanding counter doesn't inflate forever
        try:
            self._settle()
        except Exception:
            pass

    def _to_object_ref(self) -> ray_tpu.ObjectRef:
        return self._ref


class DeploymentResponseGenerator:
    """Streaming response: iterate per-item results (parity:
    ``DeploymentResponseGenerator``)."""

    def __init__(self, gen, on_done=None):
        self._gen = gen
        self._on_done = on_done
        self._settled = False

    def __iter__(self):
        try:
            for ref in self._gen:
                yield ray_tpu.get(ref, timeout=300)
        finally:
            if not self._settled:
                self._settled = True
                if self._on_done:
                    self._on_done()


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._call(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(
        self,
        deployment_name: str,
        app_name: str,
        replicas: List[Any],
        stream: bool = False,
        multiplexed_model_id: str = "",
    ):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._replicas = list(replicas)
        self._outstanding: Dict[int, int] = {i: 0 for i in range(len(replicas))}
        # controller-probed queue depths by replica id (staleness <= the
        # reconcile period): lets pow-2 see load from OTHER handles too,
        # parity with the replica probes of pow_2_scheduler.py:49
        self._probed_depths: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._stream = stream
        self._model_id = multiplexed_model_id
        # model id -> replica index this handle last routed it to
        self._model_affinity: Dict[str, int] = {}
        self._last_refresh = time.monotonic()

    def _update_replicas(self, replicas: List[Any]):
        with self._lock:
            self._replicas = list(replicas)
            self._outstanding = {i: 0 for i in range(len(replicas))}
            self._model_affinity.clear()

    def _maybe_refresh(self):
        """Pick up autoscaling changes: re-fetch the replica list from the
        controller every couple of seconds."""
        now = time.monotonic()
        if now - self._last_refresh < _REFRESH_PERIOD_S:
            return
        self._last_refresh = now
        try:
            from ray_tpu.serve.api import _CONTROLLER_NAME

            controller = ray_tpu.get_actor(_CONTROLLER_NAME)
            info = ray_tpu.get(
                controller.get_handle_info.remote(self.app_name, self.deployment_name),
                timeout=10,
            )
            if info is not None:
                new_ids = [r._actor_id for r in info[1]]
                cur_ids = [r._actor_id for r in self._replicas]
                if new_ids != cur_ids:
                    self._update_replicas(info[1])
                if len(info) > 2 and info[2]:
                    with self._lock:
                        self._probed_depths = dict(info[2])
        except Exception:
            pass

    def _pick(self, model_id: str) -> int:
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError(
                    f"deployment {self.deployment_name} has no replicas"
                )
            # multiplex-aware: stick with the replica that already loaded
            # this model unless it is heavily loaded (pow-2 fallback)
            if model_id:
                idx = self._model_affinity.get(model_id)
                if idx is not None and idx < n and self._outstanding.get(idx, 0) < 8:
                    return idx
            if n == 1:
                idx = 0
            else:
                i, j = random.sample(range(n), 2)

                def score(k: int) -> int:
                    # local in-flight plus the controller-probed global queue
                    # depth (load from other handles/proxies)
                    rid = self._replicas[k]._actor_id.hex()
                    return self._outstanding.get(k, 0) + self._probed_depths.get(
                        rid, 0
                    )

                idx = i if score(i) <= score(j) else j
            if model_id:
                self._model_affinity[model_id] = idx
            return idx

    def _call(self, method: str, args, kwargs):
        self._maybe_refresh()
        idx = self._pick(self._model_id)
        with self._lock:
            # bind the generation's counter dict: a replica-list refresh swaps
            # it out, and late done() callbacks must decrement the dict they
            # incremented (not drive the fresh one negative)
            out_map = self._outstanding
            out_map[idx] = out_map.get(idx, 0) + 1
            replica = self._replicas[idx]

        def done():
            with self._lock:
                if idx in out_map:
                    out_map[idx] -= 1

        if self._stream:
            gen = replica.handle_request_streaming.options(
                num_returns="streaming"
            ).remote(method, list(args), dict(kwargs), self._model_id)
            return DeploymentResponseGenerator(gen, on_done=done)
        ref = replica.handle_request.remote(
            method, list(args), dict(kwargs), self._model_id
        )
        return DeploymentResponse(ref, on_done=done)

    def remote(self, *args, **kwargs):
        return self._call("__call__", args, kwargs)

    def options(
        self,
        *,
        stream: Optional[bool] = None,
        multiplexed_model_id: Optional[str] = None,
        **_ignored,
    ) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name,
            self.app_name,
            self._replicas,
            stream=self._stream if stream is None else stream,
            multiplexed_model_id=(
                self._model_id if multiplexed_model_id is None else multiplexed_model_id
            ),
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def __reduce__(self):
        return (
            DeploymentHandle,
            (
                self.deployment_name,
                self.app_name,
                self._replicas,
                self._stream,
                self._model_id,
            ),
        )
