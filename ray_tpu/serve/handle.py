"""DeploymentHandle: the data-plane RPC handle between callers and replicas.

Parity: ``python/ray/serve/handle.py`` + the power-of-two-choices replica
scheduler (``replica_scheduler/pow_2_scheduler.py:49``): pick two random
replicas, send to the one with fewer requests outstanding *from this handle*
(queue-length probes are local bookkeeping here — replicas are threaded actors
so accepted requests run concurrently).
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional

import ray_tpu


class DeploymentResponse:
    """Future for one deployment call (parity: ``DeploymentResponse``)."""

    def __init__(self, ref: ray_tpu.ObjectRef, on_done=None):
        self._ref = ref
        self._on_done = on_done
        self._settled = False

    def result(self, timeout_s: Optional[float] = None) -> Any:
        try:
            value = ray_tpu.get(self._ref, timeout=timeout_s)
        finally:
            self._settle()
        return value

    def _settle(self):
        if not self._settled:
            self._settled = True
            if self._on_done:
                self._on_done()

    def __del__(self):
        # fire-and-forget callers never call result(); settle on GC so the
        # replica's outstanding counter doesn't inflate forever
        try:
            self._settle()
        except Exception:
            pass

    def _to_object_ref(self) -> ray_tpu.ObjectRef:
        return self._ref


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._call(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str, replicas: List[Any]):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._replicas = list(replicas)
        self._outstanding: Dict[int, int] = {i: 0 for i in range(len(replicas))}
        self._lock = threading.Lock()

    def _update_replicas(self, replicas: List[Any]):
        with self._lock:
            self._replicas = list(replicas)
            self._outstanding = {i: 0 for i in range(len(replicas))}

    def _pick(self) -> int:
        with self._lock:
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError(
                    f"deployment {self.deployment_name} has no replicas"
                )
            if n == 1:
                return 0
            i, j = random.sample(range(n), 2)
            return i if self._outstanding[i] <= self._outstanding[j] else j

    def _call(self, method: str, args, kwargs) -> DeploymentResponse:
        idx = self._pick()
        with self._lock:
            self._outstanding[idx] = self._outstanding.get(idx, 0) + 1
            replica = self._replicas[idx]

        def done():
            with self._lock:
                if idx in self._outstanding:
                    self._outstanding[idx] -= 1

        ref = replica.handle_request.remote(method, list(args), dict(kwargs))
        return DeploymentResponse(ref, on_done=done)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._call("__call__", args, kwargs)

    def options(self, **_ignored) -> "DeploymentHandle":
        return self

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name, self.app_name, self._replicas))
