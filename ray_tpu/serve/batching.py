"""Dynamic request batching.

Parity: ``python/ray/serve/batching.py`` (``@serve.batch``) — concurrent calls
inside a threaded replica are coalesced: the first caller becomes the batch
leader, waits ``batch_wait_timeout_s`` (or until ``max_batch_size``), runs the
wrapped function once on the gathered list, and distributes results. On TPU
this is the path to full-batch XLA inference steps (BASELINE.json config #5).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable, max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: List[dict] = []
        self._leader_active = False

    def call(self, instance, item):
        entry = {"item": item, "done": threading.Event(), "result": None, "error": None}
        with self._cv:
            self._queue.append(entry)
            lead = not self._leader_active
            if lead:
                self._leader_active = True
            else:
                self._cv.notify_all()
        if lead:
            self._run_leader(instance)
        entry["done"].wait()
        if entry["error"] is not None:
            raise entry["error"]
        return entry["result"]

    def _run_leader(self, instance):
        # the leader keeps draining batches until the queue is empty, then
        # steps down — so requests queued behind the first batch are never
        # stranded leaderless
        while True:
            deadline = time.monotonic() + self.timeout
            with self._cv:
                while len(self._queue) < self.max_batch_size:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                batch = self._queue[: self.max_batch_size]
                self._queue = self._queue[self.max_batch_size :]
                more = bool(self._queue)
                if not more:
                    self._leader_active = False
            if batch:
                self._process(batch, instance)
            if not more:
                return

    def _process(self, batch, instance):
        try:
            items = [e["item"] for e in batch]
            if instance is not None:
                results = self.fn(instance, items)
            else:
                results = self.fn(items)
            if len(results) != len(items):
                raise ValueError(
                    f"batched function returned {len(results)} results for {len(items)} inputs"
                )
            for e, r in zip(batch, results):
                e["result"] = r
        except Exception as err:  # noqa: BLE001
            for e in batch:
                e["error"] = err
        finally:
            for e in batch:
                e["done"].set()


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: coalesce concurrent calls into one list-call.

    The batcher (which holds locks/conditions) is created lazily in the
    process that executes calls, so decorated classes stay cloudpicklable
    into replicas. Creation is GIL-atomic (list.append); a lost race only
    orphans a never-used batcher — no module-global lock, because cloudpickle
    captures closure-referenced globals by value.
    """

    def wrap(fn):
        holder: list = []

        @functools.wraps(fn)
        def method(self_or_item, *rest):
            if not holder:
                from ray_tpu.serve.batching import _Batcher as B

                holder.append(B(fn, max_batch_size, batch_wait_timeout_s))
            batcher = holder[0]
            if rest:  # bound method: (self, item)
                return batcher.call(self_or_item, rest[0])
            return batcher.call(None, self_or_item)

        return method

    if _fn is not None:
        return wrap(_fn)
    return wrap
