"""HTTP proxy actor: the Serve data-plane ingress.

Parity: ``python/ray/serve/_private/proxy.py`` — per-node HTTP ingress
routing requests to application handles. The reference embeds uvicorn; here
an asyncio HTTP/1.1 server runs inside the actor (no extra deps) with:

* persistent (keep-alive) client connections;
* raw-bytes request/response passthrough (JSON remains the convention for
  ``application/json`` bodies, matching the handle protocol);
* ASGI app deployments (``serve.ingress``): the full scope + body forward
  to the replica, whose response events stream back through the handle's
  streaming path — chunked transfer out when the app streams;
* the proxy→replica hop rides the cluster's persistent actor channels (one
  connection per worker, reused for every request — the keep-alive
  equivalent of the reference's cached gRPC channels).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FuturesTimeout
from typing import Dict, Optional, Tuple
from urllib.parse import unquote, urlsplit

import ray_tpu
from ray_tpu.exceptions import GetTimeoutError
from ray_tpu.serve.exceptions import (
    DeploymentOverloadedError,
    ReplicaDiedError,
    RequestTimeoutError,
)

_PROXY_NAME = "SERVE_PROXY"
DEFAULT_PORT = 8700
_MAX_BODY = 512 * 1024 * 1024


class _HeaderMap(dict):
    """Lowercase-keyed last-value dict for the proxy's own lookups, plus
    ``raw``: the full ordered (name, value) pair list so repeated headers
    survive into the ASGI scope (the spec passes every pair through)."""

    def __init__(self):
        super().__init__()
        self.raw = []

    def add(self, name: str, value: str) -> None:
        self.raw.append((name, value))
        self[name.lower()] = value


class _NoRouteError(Exception):
    """Distinguishes route misses from user KeyErrors (which must be 500s)."""


def _error_body(status: int, message: str) -> Tuple[int, bytes, str]:
    return status, json.dumps({"error": message}).encode(), "application/json"




def _retry_after_headers(e: DeploymentOverloadedError) -> Dict[str, str]:
    import math

    # getattr: a replica-raised shed may cross the task boundary as a
    # reconstructed instance without the attribute
    after = getattr(e, "retry_after_s", 1.0) or 1.0
    return {"Retry-After": str(max(1, int(math.ceil(after))))}


@ray_tpu.remote(max_concurrency=16)
class HTTPProxy:
    def __init__(self, port: int = DEFAULT_PORT, bind_host: str = "127.0.0.1"):
        self.routes: Dict[str, str] = {}  # route_prefix -> app name
        self._handles: Dict[str, object] = {}
        self._stream_handles: Dict[str, object] = {}
        self._is_asgi: Dict[str, bool] = {}
        self._direct: Dict[str, object] = {}  # app -> DirectPool
        self.port = port
        # the address peers should dial: loopback clusters stay loopback;
        # a proxy pinned to a remote node advertises its node's outbound IP
        from ray_tpu._private.worker import get_runtime
        from ray_tpu.experimental.channel import _advertised_host

        self.host = (
            "127.0.0.1"
            if bind_host == "127.0.0.1"
            else _advertised_host(get_runtime().config.cluster_host)
        )
        # handle calls block on ray_tpu.get: they run here, off the loop
        self._pool = ThreadPoolExecutor(max_workers=64, thread_name_prefix="serve-http")
        self._loop = asyncio.new_event_loop()
        started = threading.Event()

        async def _start():
            self._server = await asyncio.start_server(
                self._handle_conn, bind_host, port, backlog=256
            )
            self.port = self._server.sockets[0].getsockname()[1]
            started.set()

        def _run_loop():
            asyncio.set_event_loop(self._loop)
            self._loop.run_until_complete(_start())
            self._loop.run_forever()

        threading.Thread(target=_run_loop, daemon=True, name="serve-http-loop").start()
        started.wait(30)

    # -- HTTP/1.1 ----------------------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    return
                if req == "bad-request":
                    await self._write_simple(
                        writer, *_error_body(400, "malformed request"), False
                    )
                    return
                method, target, headers, body, http11 = req
                conn_hdr = headers.get("connection", "").lower()
                keep = (http11 and conn_hdr != "close") or conn_hdr == "keep-alive"
                try:
                    conn_ok = await self._respond(
                        writer, method, target, headers, body, keep, reader
                    )
                except (ConnectionError, BrokenPipeError):
                    return
                if not keep or conn_ok is False:
                    return
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _read_request(self, reader):
        """Parse one HTTP/1.1 request. Headers keep BOTH views: the full
        ordered (name, value) pair list (``.raw`` — repeated Cookie/Accept/
        X-Forwarded-For headers must reach the ASGI scope intact, per spec)
        and a lowercase-keyed last-value dict for the proxy's own
        Content-Length/Connection/Transfer-Encoding lookups."""
        try:
            line = await reader.readline()
        except (ConnectionError, asyncio.LimitOverrunError):
            return None
        if not line:
            return None
        try:
            method, target, version = line.decode("latin1").strip().split(" ", 2)
        except ValueError:
            return None
        headers = _HeaderMap()
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode("latin1").partition(":")
            headers.add(k.strip(), v.strip())
        # framing headers must be unambiguous: the proxy frames the body by
        # ONE value while the full raw pair list reaches the app — repeated
        # conflicting Content-Length (or CL alongside chunked TE) is the
        # classic request-smuggling desync; reject it outright (RFC 9112 §6)
        cls = {v for k, v in headers.raw if k.lower() == "content-length"}
        if len(cls) > 1:
            return "bad-request"
        if cls and "chunked" in headers.get("transfer-encoding", "").lower():
            return "bad-request"
        if "chunked" in headers.get("transfer-encoding", "").lower():
            # chunked request body: drain it fully or the unread chunk
            # framing would desync the next keep-alive request
            chunks = []
            total = 0
            while True:
                size_line = await reader.readline()
                try:
                    size = int(size_line.strip().split(b";")[0], 16)
                except ValueError:
                    return "bad-request"
                if size == 0:
                    # consume any trailer fields up to the final blank line,
                    # or the leftovers desync the next keep-alive request
                    while True:
                        trailer = await reader.readline()
                        if trailer in (b"\r\n", b"\n", b""):
                            break
                    break
                total += size
                if total > _MAX_BODY:
                    return "bad-request"
                chunks.append(await reader.readexactly(size))
                await reader.readexactly(2)  # chunk CRLF
            return method, target, headers, b"".join(chunks), version.endswith("1.1")
        try:
            length = int(headers.get("content-length") or 0)
        except ValueError:
            return "bad-request"
        if length > _MAX_BODY:
            return "bad-request"
        body = await reader.readexactly(length) if length else b""
        return method, target, headers, body, version.endswith("1.1")

    async def _respond(self, writer, method, target, headers, body, keep, reader=None):
        """Returns False when the connection must be dropped (a truncated
        chunked stream cannot be reused, or it was consumed by a websocket
        upgrade)."""
        split = urlsplit(target)
        path = unquote(split.path)
        app = self._match(path)
        if app is None:
            await self._write_simple(
                writer, *_error_body(404, f"no route for {path}"), keep
            )
            return True
        if (
            reader is not None
            and headers.get("upgrade", "").lower() == "websocket"
            and "upgrade" in headers.get("connection", "").lower()
        ):
            return await self._respond_websocket(
                reader, writer, app, path, split.query, headers, keep
            )
        if self._is_asgi.get(app):
            return await self._respond_asgi(
                writer, app, method, path, split.query, headers, body, keep
            )
        loop = asyncio.get_running_loop()
        extra_headers = None
        ctx = self._mint_trace()
        try:
            status, blob, ctype = await loop.run_in_executor(
                self._pool, self._call_plain_traced, app, path, headers, body,
                ctx,
            )
        except DeploymentOverloadedError as e:
            # load shedding: fast 503 + Retry-After instead of queueing the
            # request into a guaranteed timeout
            status, blob, ctype = _error_body(503, str(e))
            extra_headers = _retry_after_headers(e)
        except (RequestTimeoutError, GetTimeoutError) as e:
            status, blob, ctype = _error_body(504, str(e))
        except Exception as e:  # noqa: BLE001
            status, blob, ctype = _error_body(500, str(e))
        if ctx is not None:
            # the request's trace id rides the response so a slow call can
            # be inspected with `ray_tpu trace <id>` directly
            extra_headers = dict(extra_headers or {})
            extra_headers["x-raytpu-trace-id"] = ctx.trace_id
        await self._write_simple(writer, status, blob, ctype, keep, extra_headers)
        return True

    @staticmethod
    def _mint_trace():
        """Root trace context for one proxy request (the serve-plane entry
        point); None when tracing is off."""
        from ray_tpu.util import tracing

        return tracing.new_root() if tracing.tracing_enabled() else None

    def _call_plain_traced(self, app, path, headers, body, ctx):
        """Pool-side wrapper: activate the request's root context and record
        the proxy span (status + handle/replica sections nest under it)."""
        if ctx is None:
            return self._call_plain(app, headers, body)
        from ray_tpu._private.profiling import traced_section
        from ray_tpu.util import tracing

        with tracing.scope(ctx):
            with traced_section(
                f"serve:proxy:{path}", {"app": app, "entry": "http"}
            ) as sx:
                status, blob, ctype = self._call_plain(app, headers, body)
                sx["status"] = status
                return status, blob, ctype

    def _match(self, path: str) -> Optional[str]:
        for prefix, app in sorted(self.routes.items(), key=lambda kv: -len(kv[0])):
            if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                return app
        return None

    # -- plain (handle-protocol) deployments ------------------------------

    def _call_plain(self, app, headers, body) -> Tuple[int, bytes, str]:
        """Runs on the pool: JSON convention for json bodies, raw bytes
        otherwise; responses map by type (bytes -> octet-stream, str ->
        text, else JSON). Dispatch rides the direct proxy->replica channel
        when available, else the handle path."""
        ctype = headers.get("content-type", "")
        if body and "json" not in ctype and ctype:
            args = (body,)
        else:
            payload = json.loads(body) if body else None
            args = (payload,) if payload is not None else ()
        result = self._dispatch(app, "__call__", args)
        if isinstance(result, (bytes, bytearray, memoryview)):
            return 200, bytes(result), "application/octet-stream"
        if isinstance(result, str):
            return 200, result.encode(), "text/plain; charset=utf-8"
        return 200, json.dumps({"result": result}, default=str).encode(), "application/json"

    def _dispatch(self, app, method, args):
        from ray_tpu.serve._direct import _DirectUnavailable

        handle = self._handles[app]
        timeout_s = float(handle._cfg.get("request_timeout_s") or 120.0)
        pool = self._direct.get(app)
        if pool is not None:
            # admission control covers the direct path too: the handle only
            # sees its own in-flight count, so fold in the pool's
            handle._check_admission(extra_load=pool.total_outstanding())
            try:
                return pool.call(method, args, {}, timeout=timeout_s)
            except _DirectUnavailable:
                pass
            # ReplicaDiedError propagates: torn work must NOT silently
            # re-execute through the handle path
        return handle._call(method, args, {}).result(timeout_s=timeout_s)

    async def _write_simple(self, writer, status, blob, ctype, keep,
                            extra_headers=None):
        extra = "".join(
            f"{k}: {v}\r\n" for k, v in (extra_headers or {}).items()
        )
        writer.write(
            (
                f"HTTP/1.1 {status} {_REASONS.get(status, 'OK')}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(blob)}\r\n"
                + extra
                + f"Connection: {'keep-alive' if keep else 'close'}\r\n\r\n"
            ).encode("latin1")
        )
        writer.write(blob)
        await writer.drain()

    # -- ASGI deployments --------------------------------------------------

    def _check_admission(self, app):
        """Per-deployment admission bound, shared by every ingress path;
        raises DeploymentOverloadedError when the deployment should shed."""
        handle = self._handles.get(app)
        if handle is None:
            return
        pool = self._direct.get(app)
        handle._check_admission(
            extra_load=pool.total_outstanding() if pool is not None else 0
        )

    async def _respond_asgi(self, writer, app, method, path, query, headers, body, keep):
        """Returns False when the connection is no longer reusable (client
        vanished or the chunked stream was truncated by a replica error)."""
        try:
            self._check_admission(app)
        except DeploymentOverloadedError as e:
            await self._write_simple(
                writer, *_error_body(503, str(e)), keep, _retry_after_headers(e)
            )
            return True
        scope = {
            "type": "http",
            "http_version": "1.1",
            "method": method.upper(),
            "path": path,
            "raw_path": path.encode(),
            "query_string": query.encode("latin1"),
            "root_path": "",
            "headers": [
                (k.lower().encode("latin1"), v.encode("latin1"))
                for k, v in getattr(headers, "raw", list(headers.items()))
            ],
        }
        loop = asyncio.get_running_loop()
        # bounded: a slow/vanished client must backpressure the pump, not
        # buffer an SSE stream forever
        q: asyncio.Queue = asyncio.Queue(maxsize=64)
        cancelled = threading.Event()

        def put(event) -> bool:
            """Blocking put from the pump thread; False once cancelled."""
            while not cancelled.is_set():
                fut = asyncio.run_coroutine_threadsafe(q.put(event), loop)
                try:
                    fut.result(timeout=1.0)
                    return True
                except TimeoutError:
                    if not fut.cancel():
                        # completed in the cancel window: the event IS
                        # enqueued — re-submitting would duplicate a chunk
                        return True
                except Exception:
                    return False
            return False

        ctx = self._mint_trace()

        def pump():
            from ray_tpu._private.profiling import traced_section
            from ray_tpu.serve._direct import _DirectUnavailable
            from ray_tpu.util import tracing

            try:
                with tracing.scope(ctx), traced_section(
                    f"serve:proxy:{path}", {"app": app, "entry": "asgi"}
                ) if ctx is not None else contextlib.nullcontext({}) as sx:
                    import time as _time

                    t0 = _time.perf_counter()
                    sent = 0

                    def fwd(event) -> bool:
                        nonlocal sent
                        if sent == 0 and ctx is not None:
                            # TTFT: request in -> first response event out
                            sx["ttft_ms"] = round(
                                (_time.perf_counter() - t0) * 1e3, 3
                            )
                        sent += 1
                        return put(event)

                    pool = self._direct.get(app)
                    if pool is not None:
                        forwarded = False
                        try:
                            for event in pool.call_streaming(
                                "__asgi__", (scope, body), {}
                            ):
                                forwarded = True
                                if not fwd(event):
                                    return  # client gone; channel cleans up
                            put(None)
                            return
                        except _DirectUnavailable:
                            if forwarded:
                                raise  # mid-stream break: don't replay chunks
                            # nothing sent yet: fall through to handle path
                    handle = self._stream_handles[app]
                    for event in handle._call("__asgi__", (scope, body), {}):
                        if not fwd(event):
                            return
                    put(None)
            except BaseException as e:  # noqa: BLE001
                put(e)

        self._pool.submit(pump)
        extra_headers = (
            {"x-raytpu-trace-id": ctx.trace_id} if ctx is not None else None
        )
        try:
            return await self._write_asgi_response(
                writer, q, keep, extra_headers
            )
        finally:
            cancelled.set()

    async def _write_asgi_response(self, writer, q, keep,
                                   extra_headers=None) -> bool:
        first = await q.get()
        if first is None or isinstance(first, BaseException):
            if isinstance(first, DeploymentOverloadedError):
                # replica-side shed (e.g. KV-aware admission in an LLM
                # engine) raised before the first response event: same
                # 503 + Retry-After surface as proxy-side admission
                hdrs = dict(extra_headers or {})
                hdrs.update(_retry_after_headers(first))
                await self._write_simple(
                    writer, *_error_body(503, str(first)), keep, hdrs
                )
                return True
            msg = str(first) if first is not None else "empty ASGI response"
            await self._write_simple(
                writer, *_error_body(500, msg), keep, extra_headers
            )
            return True
        _, status, hdr_pairs = first
        # peek the next event to choose Content-Length vs chunked
        second = await q.get()
        hdr_lines = [
            f"{k.decode('latin1')}: {v.decode('latin1')}\r\n"
            for k, v in hdr_pairs
            if k.lower() not in (b"content-length", b"transfer-encoding", b"connection")
        ]
        for k, v in (extra_headers or {}).items():
            hdr_lines.append(f"{k}: {v}\r\n")
        conn_line = f"Connection: {'keep-alive' if keep else 'close'}\r\n"
        head = f"HTTP/1.1 {status} {_REASONS.get(status, 'OK')}\r\n" + "".join(hdr_lines)
        bodiless = second is None  # start followed by end: 204/304 pattern
        if bodiless or (
            isinstance(second, tuple) and second[0] == "body" and not second[2]
        ):
            blob = b"" if bodiless else second[1]
            writer.write(
                (head + f"Content-Length: {len(blob)}\r\n" + conn_line + "\r\n").encode("latin1")
            )
            writer.write(blob)
            await writer.drain()
            return True
        # streaming: chunked transfer encoding
        writer.write((head + "Transfer-Encoding: chunked\r\n" + conn_line + "\r\n").encode("latin1"))
        event = second
        while True:
            if event is None:
                break
            if isinstance(event, BaseException):
                # replica died mid-stream: DROP the connection without the
                # terminal chunk so the client sees truncation, not success
                return False
            if event[0] == "body":
                chunk = event[1]
                if chunk:
                    writer.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                    await writer.drain()
                if not event[2]:
                    break
            event = await q.get()
        writer.write(b"0\r\n\r\n")
        await writer.drain()
        return True

    # -- websocket upgrades ------------------------------------------------

    async def _respond_websocket(self, reader, writer, app, path, query, headers, keep):
        """RFC 6455 upgrade + frame relay (parity: the reference proxies
        websocket ASGI scopes via uvicorn, ``serve/_private/proxy.py``).
        Client frames relay to the replica as ``websocket.receive`` events
        over a dedicated direct-plane connection; the app's ``websocket.send``
        events come back as frames. Returns False when the connection was
        consumed by the session (always, after a 101)."""
        from ray_tpu.serve import _ws as ws
        from ray_tpu.serve._direct import _DirectUnavailable

        key = headers.get("sec-websocket-key")
        if not key:
            await self._write_simple(writer, *_error_body(400, "missing Sec-WebSocket-Key"), keep)
            return True
        if headers.get("sec-websocket-version", "13") != "13":
            writer.write(
                b"HTTP/1.1 426 Upgrade Required\r\nSec-WebSocket-Version: 13\r\n"
                b"Content-Length: 0\r\nConnection: close\r\n\r\n"
            )
            await writer.drain()
            return False
        if not self._is_asgi.get(app):
            await self._write_simple(
                writer, *_error_body(400, "route does not mount an ASGI app"), keep
            )
            return True
        try:
            # new sessions are load too: shed before dedicating a replica
            # serving thread to the socket
            self._check_admission(app)
        except DeploymentOverloadedError as e:
            await self._write_simple(
                writer, *_error_body(503, str(e)), keep, _retry_after_headers(e)
            )
            return True
        pool = self._direct.get(app)
        loop = asyncio.get_running_loop()
        conn = None
        if pool is not None:
            try:
                conn = await loop.run_in_executor(self._pool, pool.open_dedicated)
            except _DirectUnavailable:
                conn = None
            except Exception:
                conn = None
        if conn is None:
            # websockets need the bidirectional direct plane; the handle
            # path is request->stream only
            await self._write_simple(
                writer, *_error_body(503, "no live replica channel for websocket"), keep
            )
            return True

        scope = {
            "type": "websocket",
            "http_version": "1.1",
            "scheme": "ws",
            "path": path,
            "raw_path": path.encode(),
            "query_string": query.encode("latin1"),
            "root_path": "",
            "headers": [
                (k.lower().encode("latin1"), v.encode("latin1"))
                for k, v in getattr(headers, "raw", list(headers.items()))
            ],
            "subprotocols": [
                s.strip()
                for s in headers.get("sec-websocket-protocol", "").split(",")
                if s.strip()
            ],
        }

        q: asyncio.Queue = asyncio.Queue(maxsize=64)
        cancelled = threading.Event()

        def put(event) -> bool:
            while not cancelled.is_set():
                fut = asyncio.run_coroutine_threadsafe(q.put(event), loop)
                try:
                    fut.result(timeout=1.0)
                    return True
                except _FuturesTimeout:
                    # NOT builtin TimeoutError: on Python 3.8-3.10 the
                    # futures timeout is a distinct class, and letting it
                    # fall into the generic handler killed the pump on a
                    # 1s backpressure stall
                    if not fut.cancel():
                        return True
                except Exception:
                    return False
            return False

        # session root span: minted here (not in the pump thread) so the 101
        # response can carry the trace id and the session span records below
        ws_ctx = self._mint_trace()
        ws_t0 = time.time()

        def pump_down():
            import pickle as _pickle

            try:
                conn.send(
                    ("__ws__", [scope], {}, "", True,
                     ws_ctx.to_dict() if ws_ctx is not None else None)
                )
                while True:
                    kind, payload = conn.recv()
                    if kind == "evt":
                        if not put(payload):
                            return
                    elif kind == "end":
                        put(None)
                        return
                    else:  # "err"
                        put(_pickle.loads(payload))
                        return
            except (EOFError, OSError, BrokenPipeError):
                put(ConnectionError("replica connection lost"))
            except BaseException as e:  # noqa: BLE001
                put(e)

        # sessions are long-lived: dedicated threads, NOT the shared request
        # pool — 64 idle websockets must not starve plain HTTP dispatch
        threading.Thread(target=pump_down, daemon=True, name="ws-down").start()
        up_q: "queue.Queue" = queue.Queue(maxsize=256)

        def pump_up():
            try:
                while True:
                    ev = up_q.get()
                    if ev is None:
                        return
                    conn.send(("msg", ev))
            except (OSError, EOFError, BrokenPipeError):
                pass

        up_thread = threading.Thread(target=pump_up, daemon=True, name="ws-up")
        up_thread.start()
        try:
            # bounded: an app that hangs before accept/close must not leak
            # the client socket, both pump threads, and a dedicated replica
            # serving thread per retried connection
            try:
                first = await asyncio.wait_for(q.get(), timeout=60.0)
            except asyncio.TimeoutError:
                await self._write_simple(
                    writer, *_error_body(500, "app never completed the handshake"), keep
                )
                return True
            if isinstance(first, dict) and first.get("type") == "websocket.accept":
                extra = [
                    f"{k.decode('latin1')}: {v.decode('latin1')}\r\n"
                    for k, v in first.get("headers", [])
                ]
                sub = first.get("subprotocol")
                if sub:
                    extra.append(f"Sec-WebSocket-Protocol: {sub}\r\n")
                if ws_ctx is not None:
                    # the session's trace id rides the upgrade response so
                    # a slow websocket can be fed to `ray_tpu trace <id>`
                    extra.append(f"x-raytpu-trace-id: {ws_ctx.trace_id}\r\n")
                writer.write(
                    (
                        "HTTP/1.1 101 Switching Protocols\r\n"
                        "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                        f"Sec-WebSocket-Accept: {ws.accept_key(key)}\r\n"
                        + "".join(extra)
                        + "\r\n"
                    ).encode("latin1")
                )
                await writer.drain()
            elif isinstance(first, dict) and first.get("type") == "websocket.close":
                # rejected before accept -> 403, per the ASGI spec
                await self._write_simple(writer, 403, b"", "text/plain", keep)
                return True
            else:
                msg = str(first) if first is not None else "app closed without accepting"
                await self._write_simple(writer, *_error_body(500, msg), keep)
                return True

            # -- accepted: relay until either side closes ------------------
            async def send_up(event) -> None:
                # enqueue for the session's sender thread; an async retry
                # loop gives backpressure without parking a pool thread
                while True:
                    try:
                        up_q.put_nowait(event)
                        return
                    except queue.Full:
                        await asyncio.sleep(0.02)

            async def upstream():
                frames = ws.MessageReader(reader)
                try:
                    while True:
                        op, payload = await frames.next()
                        if op == ws.OP_CLOSE:
                            code, _reason = ws.parse_close(payload)
                            try:
                                writer.write(ws.encode_close(code))
                                await writer.drain()
                            except (ConnectionError, OSError):
                                pass
                            await send_up(
                                {"type": "websocket.disconnect", "code": code}
                            )
                            return
                        if op == ws.OP_PING:
                            writer.write(ws.encode_frame(ws.OP_PONG, payload))
                            await writer.drain()
                            continue
                        if op == ws.OP_PONG:
                            continue
                        ev = {"type": "websocket.receive"}
                        if op == ws.OP_TEXT:
                            ev["text"] = payload.decode("utf-8")
                        else:
                            ev["bytes"] = payload
                        await send_up(ev)
                except (ConnectionError, OSError, EOFError, ValueError,
                        asyncio.IncompleteReadError):
                    try:
                        up_q.put_nowait(
                            {"type": "websocket.disconnect", "code": 1006}
                        )
                    except queue.Full:
                        pass

            up_task = asyncio.ensure_future(upstream())
            try:
                while True:
                    event = await q.get()
                    if event is None:
                        # app returned without an explicit close
                        writer.write(ws.encode_close(1000))
                        await writer.drain()
                        return False
                    if isinstance(event, BaseException):
                        try:
                            writer.write(ws.encode_close(1011, "internal error"))
                            await writer.drain()
                        except (ConnectionError, OSError):
                            pass
                        return False
                    t = event.get("type")
                    if t == "websocket.send":
                        if event.get("text") is not None:
                            frame = ws.encode_frame(
                                ws.OP_TEXT, event["text"].encode("utf-8")
                            )
                        else:
                            frame = ws.encode_frame(
                                ws.OP_BINARY, bytes(event.get("bytes") or b"")
                            )
                        writer.write(frame)
                        await writer.drain()
                    elif t == "websocket.close":
                        writer.write(
                            ws.encode_close(
                                int(event.get("code", 1000)),
                                str(event.get("reason") or ""),
                            )
                        )
                        await writer.drain()
                        return False
            finally:
                up_task.cancel()
        except (ConnectionError, OSError):
            return False
        finally:
            cancelled.set()
            try:
                up_q.put_nowait(None)  # stop the sender thread
            except queue.Full:
                pass  # it will exit on the closed conn instead
            try:
                conn.close()
            except OSError:
                pass
            if ws_ctx is not None:
                # session span: the trace's proxy entry node (replica-side
                # spans and nested submissions parent to it), duration =
                # whole websocket session
                try:
                    import os as _os

                    from ray_tpu._private import telemetry as _telemetry

                    end = time.time()
                    _telemetry.record_span(
                        {
                            "event": f"serve:proxy:ws:{path}",
                            "start": ws_t0,
                            "end": end,
                            "duration_ms": (end - ws_t0) * 1e3,
                            "pid": _os.getpid(),
                            "extra": {"app": app, "entry": "websocket",
                                      **ws_ctx.to_dict()},
                        }
                    )
                except Exception:
                    pass
        return False

    # -- control -----------------------------------------------------------

    def _route(self, path: str, payload):
        """In-process dispatch (kept for tests/back-compat)."""
        app = self._match(path)
        if app is None:
            raise _NoRouteError(path)
        handle = self._handles[app]
        resp = handle.remote(payload) if payload is not None else handle.remote()
        return resp.result(timeout_s=120)

    def add_route(self, route_prefix: str, app_name: str, handle):
        self.routes[route_prefix] = app_name
        self._handles[app_name] = handle
        self._stream_handles[app_name] = handle.options(stream=True)
        is_asgi = False
        try:
            replicas = getattr(handle, "_replicas", None) or []
            if replicas:
                is_asgi = bool(
                    ray_tpu.get(replicas[0].is_asgi.remote(), timeout=30)
                )
        except Exception:
            is_asgi = False
        self._is_asgi[app_name] = is_asgi
        # direct proxy->replica data plane (head out of the request path);
        # a re-added route must close the prior pool's channels first
        old = self._direct.pop(app_name, None)
        if old is not None:
            try:
                old.close()
            except Exception:
                pass
        try:
            from ray_tpu._private.worker import get_runtime
            from ray_tpu.serve._direct import DirectPool

            key = get_runtime().config.cluster_auth_key.encode()
            self._direct[app_name] = DirectPool(handle, key)
        except Exception:
            self._direct.pop(app_name, None)
        return self.port

    def _refresh_direct(self):
        for pool in self._direct.values():
            try:
                pool.refresh()
            except Exception:
                pass

    def remove_route(self, route_prefix: str):
        app = self.routes.pop(route_prefix, None)
        if app:
            self._handles.pop(app, None)
            self._stream_handles.pop(app, None)
            self._is_asgi.pop(app, None)
            pool = self._direct.pop(app, None)
            if pool is not None:
                try:
                    pool.close()
                except Exception:
                    pass
        return True

    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)


_REASONS = {
    200: "OK",
    400: "Bad Request",
    403: "Forbidden",
    404: "Not Found",
    500: "Internal Server Error",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}


def ensure_proxy(controller, app_name: str, route_prefix: str, port: int = DEFAULT_PORT):
    from ray_tpu.serve.api import get_app_handle

    try:
        proxy = ray_tpu.get_actor(_PROXY_NAME)
    except ValueError:
        try:
            proxy = HTTPProxy.options(name=_PROXY_NAME, num_cpus=0).remote(port)
        except ValueError:
            proxy = ray_tpu.get_actor(_PROXY_NAME)
    handle = get_app_handle(app_name)
    ray_tpu.get(proxy.add_route.remote(route_prefix, app_name, handle), timeout=60)
    try:
        ray_tpu.get(
            controller.register_route.remote(route_prefix, app_name), timeout=60
        )
    except Exception:
        pass
    return proxy


def start_node_proxies() -> Dict[str, Tuple[str, int]]:
    """One HTTP ingress per alive node (parity: the reference's ProxyState
    keeping a proxy actor on every node, ``_private/proxy_state.py``): each
    proxy is pinned to its node and serves every registered route through
    its own handles (pow-2 + probed queue depths). Returns
    ``{node_id_hex: (host, port)}``; ports are ephemeral per node."""
    from ray_tpu.serve.api import _get_or_create_controller, get_app_handle
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    controller = _get_or_create_controller()
    routes = ray_tpu.get(controller.get_routes.remote(), timeout=60)
    # one handle fetch per app (not per node x route); skip apps deleted
    # since their route was registered
    handles = {}
    for app in set(routes.values()):
        try:
            handles[app] = get_app_handle(app)
        except ValueError:
            pass
    out: Dict[str, Tuple[str, int]] = {}
    for node in ray_tpu.nodes():
        if not node["alive"]:
            continue
        nid = node["node_id"]
        name = f"{_PROXY_NAME}:{nid[:12]}"
        try:
            proxy = ray_tpu.get_actor(name)
        except ValueError:
            try:
                proxy = HTTPProxy.options(
                    name=name,
                    num_cpus=0,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=nid, soft=False
                    ),
                ).remote(0, bind_host="0.0.0.0")  # ephemeral port per node
            except ValueError:
                proxy = ray_tpu.get_actor(name)
        for prefix, app in routes.items():
            if app in handles:
                ray_tpu.get(
                    proxy.add_route.remote(prefix, app, handles[app]),
                    timeout=60,
                )
        out[nid] = tuple(ray_tpu.get(proxy.address.remote(), timeout=60))
    return out
