"""HTTP proxy actor.

Parity: ``python/ray/serve/_private/proxy.py`` — per-cluster HTTP ingress
routing requests to application handles. The reference embeds uvicorn; here a
stdlib ThreadingHTTPServer runs inside a threaded actor (no extra deps), with
JSON request/response bodies.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

import ray_tpu

_PROXY_NAME = "SERVE_PROXY"
DEFAULT_PORT = 8700


class _NoRouteError(Exception):
    """Distinguishes route misses from user KeyErrors (which must be 500s)."""


@ray_tpu.remote(max_concurrency=16)
class HTTPProxy:
    def __init__(self, port: int = DEFAULT_PORT, bind_host: str = "127.0.0.1"):
        self.routes: Dict[str, str] = {}  # route_prefix -> app name
        self._handles: Dict[str, object] = {}
        self.port = port
        # the address peers should dial: loopback clusters stay loopback;
        # a proxy pinned to a remote node advertises its node's outbound IP
        from ray_tpu._private.worker import get_runtime
        from ray_tpu.experimental.channel import _advertised_host

        self.host = (
            "127.0.0.1"
            if bind_host == "127.0.0.1"
            else _advertised_host(get_runtime().config.cluster_host)
        )
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _dispatch(self):
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else b""
                    payload = json.loads(body) if body else None
                    result = proxy._route(self.path, payload)
                    blob = json.dumps({"result": result}, default=str).encode()
                    self.send_response(200)
                except _NoRouteError:
                    blob = json.dumps({"error": f"no route for {self.path}"}).encode()
                    self.send_response(404)
                except Exception as e:  # noqa: BLE001
                    blob = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            do_GET = _dispatch
            do_POST = _dispatch

        self._server = ThreadingHTTPServer((bind_host, port), Handler)
        self.port = self._server.server_address[1]  # resolved when port=0
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def _route(self, path: str, payload):
        for prefix, app in sorted(self.routes.items(), key=lambda kv: -len(kv[0])):
            if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                handle = self._handles[app]
                if payload is None:
                    resp = handle.remote()
                else:
                    resp = handle.remote(payload)
                return resp.result(timeout_s=120)
        raise _NoRouteError(path)

    def add_route(self, route_prefix: str, app_name: str, handle):
        self.routes[route_prefix] = app_name
        self._handles[app_name] = handle
        return self.port

    def remove_route(self, route_prefix: str):
        app = self.routes.pop(route_prefix, None)
        if app:
            self._handles.pop(app, None)
        return True

    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)


def ensure_proxy(controller, app_name: str, route_prefix: str, port: int = DEFAULT_PORT):
    from ray_tpu.serve.api import get_app_handle

    try:
        proxy = ray_tpu.get_actor(_PROXY_NAME)
    except ValueError:
        try:
            proxy = HTTPProxy.options(name=_PROXY_NAME, num_cpus=0).remote(port)
        except ValueError:
            proxy = ray_tpu.get_actor(_PROXY_NAME)
    handle = get_app_handle(app_name)
    ray_tpu.get(proxy.add_route.remote(route_prefix, app_name, handle), timeout=60)
    try:
        ray_tpu.get(
            controller.register_route.remote(route_prefix, app_name), timeout=60
        )
    except Exception:
        pass
    return proxy


def start_node_proxies() -> Dict[str, Tuple[str, int]]:
    """One HTTP ingress per alive node (parity: the reference's ProxyState
    keeping a proxy actor on every node, ``_private/proxy_state.py``): each
    proxy is pinned to its node and serves every registered route through
    its own handles (pow-2 + probed queue depths). Returns
    ``{node_id_hex: (host, port)}``; ports are ephemeral per node."""
    from ray_tpu.serve.api import _get_or_create_controller, get_app_handle
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    controller = _get_or_create_controller()
    routes = ray_tpu.get(controller.get_routes.remote(), timeout=60)
    # one handle fetch per app (not per node x route); skip apps deleted
    # since their route was registered
    handles = {}
    for app in set(routes.values()):
        try:
            handles[app] = get_app_handle(app)
        except ValueError:
            pass
    out: Dict[str, Tuple[str, int]] = {}
    for node in ray_tpu.nodes():
        if not node["alive"]:
            continue
        nid = node["node_id"]
        name = f"{_PROXY_NAME}:{nid[:12]}"
        try:
            proxy = ray_tpu.get_actor(name)
        except ValueError:
            try:
                proxy = HTTPProxy.options(
                    name=name,
                    num_cpus=0,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=nid, soft=False
                    ),
                ).remote(0, bind_host="0.0.0.0")  # ephemeral port per node
            except ValueError:
                proxy = ray_tpu.get_actor(name)
        for prefix, app in routes.items():
            if app in handles:
                ray_tpu.get(
                    proxy.add_route.remote(prefix, app, handles[app]),
                    timeout=60,
                )
        out[nid] = tuple(ray_tpu.get(proxy.address.remote(), timeout=60))
    return out
