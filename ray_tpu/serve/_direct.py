"""Direct proxy→replica data plane.

Parity: the reference proxy speaks gRPC straight to replica processes
(``python/ray/serve/_private/proxy.py`` → replica ``ASGIReplicaWrapper``),
bypassing the control plane per request. Here every Replica hosts a small
authenticated socket server inside its worker process; proxies hold
persistent connections (the keep-alive hop) and exchange framed-pickle
request/response pairs — the cluster head is no longer in the per-request
path. Handle-path dispatch remains the fallback when a direct channel
breaks (replica restarting / autoscaled away).
"""

from __future__ import annotations

import contextlib
import pickle
import threading
from multiprocessing.connection import Client, Listener
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle


class DirectReplicaServer:
    """Runs inside the replica worker: serves requests over persistent
    authenticated connections, executing through the SAME gate/ongoing
    accounting as handle-path requests (autoscaling sees both)."""

    def __init__(self, replica, auth_key: bytes, host: str = "0.0.0.0"):
        self._replica = replica
        self._listener = Listener((host, 0), backlog=64, authkey=auth_key)
        self._stop = False
        threading.Thread(
            target=self._accept_loop, daemon=True, name="serve-direct"
        ).start()

    @property
    def port(self) -> int:
        return tuple(self._listener.address)[1]

    def _accept_loop(self):
        while not self._stop:
            try:
                conn = self._listener.accept()
            except Exception:
                # AuthenticationError (a failed HMAC challenge from a
                # scanner or stale-key proxy) is NOT an OSError; the accept
                # loop must survive it or the replica permanently loses its
                # direct plane
                if self._stop:
                    return
                continue
            from ray_tpu._private.object_transfer import set_nodelay

            set_nodelay(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn):
        from ray_tpu.util import tracing as _tracing

        try:
            while True:
                msg = conn.recv()
                method, args, kwargs, model_id, stream = msg[:5]
                # optional 6th frame element: the caller's trace context —
                # activated for this request so replica spans join the
                # proxy's trace (frames from older proxies simply lack it)
                ctx = None
                if len(msg) > 5 and msg[5]:
                    try:
                        ctx = _tracing.TraceContext.from_dict(msg[5])
                    except Exception:
                        ctx = None
                with _tracing.scope(ctx) if ctx is not None else (
                    contextlib.nullcontext()
                ):
                    done = self._serve_one(
                        conn, method, args, kwargs, model_id, stream
                    )
                if done:
                    return
        except (EOFError, OSError, BrokenPipeError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_one(self, conn, method, args, kwargs, model_id, stream) -> bool:
        """Handle one framed request; True = the connection is consumed
        (websocket sessions never return to request/response framing)."""
        if method == "__ws__":
            # the connection becomes a dedicated bidirectional
            # websocket session channel; it never returns to
            # request/response framing. A drain rejection (or any
            # pre-session failure) goes back as a typed error frame
            # so the proxy answers the upgrade cleanly instead of
            # dropping the socket.
            try:
                self._replica.handle_websocket(conn, args[0])
            except Exception as e:  # noqa: BLE001
                try:
                    blob = cloudpickle.dumps(e)
                except Exception:
                    blob = pickle.dumps(RuntimeError(str(e)))
                try:
                    conn.send(("err", blob))
                except (OSError, BrokenPipeError):
                    pass
            return True
        try:
            # the ("started", None) frame is the replica-side
            # started-marker: a channel that breaks BEFORE the proxy
            # saw it provably never executed this request (safe to
            # retry elsewhere); a break after it is torn work.
            # Draining rejections are checked first so they are
            # never marked started.
            if getattr(self._replica, "_draining", False):
                self._replica._reject_if_draining()
            if stream:
                conn.send(("started", None))
                for item in self._replica.handle_request_streaming(
                    method, args, kwargs, model_id
                ):
                    conn.send(("item", item))
                conn.send(("end", None))
            else:
                conn.send(("started", None))
                result = self._replica.handle_request(
                    method, args, kwargs, model_id
                )
                conn.send(("ok", result))
        except Exception as e:  # noqa: BLE001
            try:
                blob = cloudpickle.dumps(e)
            except Exception:
                blob = pickle.dumps(RuntimeError(str(e)))
            conn.send(("err", blob))
        return False

    def close(self):
        self._stop = True
        try:
            self._listener.close()
        except OSError:
            pass


class DirectChannel:
    """Proxy-side persistent connection to one replica's direct server.

    A channel whose request/response framing can no longer be trusted (recv
    timeout, stream abandoned mid-flight) marks itself broken; the pool
    re-dials a replacement lazily.
    """

    CALL_TIMEOUT_S = 120.0
    STREAM_FRAME_TIMEOUT_S = 300.0

    def __init__(self, address, auth_key: bytes):
        self._address = tuple(address)
        self._auth = auth_key
        self._conn = Client(self._address, authkey=auth_key)
        from ray_tpu._private.object_transfer import set_nodelay

        set_nodelay(self._conn)
        self._lock = threading.Lock()
        self.broken = False

    def _recv(self, timeout: float):
        try:
            ready = self._conn.poll(timeout)
        except (OSError, EOFError) as e:
            self.broken = True
            self.close()
            raise _ChannelBroken(str(e)) from e
        if not ready:
            self.broken = True
            self.close()
            # the reply may still arrive later, so this socket's framing can
            # no longer be trusted (channel dies), but the REPLICA is not
            # dead — tag it so the pool raises a timeout, not replica-death
            err = _ChannelBroken(
                f"direct replica call timed out after {timeout}s"
            )
            err.timed_out = True
            raise err
        try:
            return self._conn.recv()
        except (OSError, EOFError) as e:
            self.broken = True
            self.close()
            raise _ChannelBroken(str(e)) from e

    def _send(self, msg):
        try:
            self._conn.send(msg)
        except (OSError, EOFError, BrokenPipeError) as e:
            self.broken = True
            self.close()
            raise _ChannelBroken(str(e)) from e

    @staticmethod
    def _ctx_frame():
        """The caller's trace context as the frame's optional 6th element
        (None when untraced) — replica spans join the proxy's span tree."""
        from ray_tpu.util.tracing import context_args

        return context_args() or None

    def call(self, method: str, args, kwargs, model_id: str = "", timeout=None):
        timeout = timeout or self.CALL_TIMEOUT_S
        started = False
        with self._lock:
            try:
                self._send(
                    (method, list(args), dict(kwargs), model_id, False,
                     self._ctx_frame())
                )
                kind, payload = self._recv(timeout)
                if kind == "started":
                    started = True
                    kind, payload = self._recv(timeout)
            except _ChannelBroken as e:
                # started-marker: a break before the replica's "started"
                # frame means this request never executed — safe to retry
                e.started = started
                raise
        if kind == "ok":
            return payload
        # an APPLICATION exception (may subclass OSError!) — it must reach
        # the caller untouched, never be mistaken for a transport failure
        raise pickle.loads(payload)

    def call_streaming(self, method: str, args, kwargs, model_id: str = ""):
        completed = False
        started = False
        items_sent = 0
        with self._lock:
            try:
                self._send(
                    (method, list(args), dict(kwargs), model_id, True,
                     self._ctx_frame())
                )
                while True:
                    try:
                        kind, payload = self._recv(self.STREAM_FRAME_TIMEOUT_S)
                    except _ChannelBroken as e:
                        e.started = started
                        e.items_sent = items_sent
                        raise
                    if kind == "started":
                        started = True
                    elif kind == "item":
                        items_sent += 1
                        yield payload
                    elif kind == "end":
                        completed = True
                        return
                    else:
                        completed = True  # framing intact: error frame ends it
                        raise pickle.loads(payload)
            finally:
                if not completed:
                    # abandoned mid-stream (client went away): unread frames
                    # would desync the next request on this socket
                    self.broken = True
                    self.close()

    def close(self):
        try:
            self._conn.close()
        except OSError:
            pass


class DirectPool:
    """Pow-2 routed pool of direct channels for one application.

    Several channels per replica so concurrent proxy threads don't serialize
    on one socket; broken channels evict the replica until the next refresh
    (the caller falls back to the handle path meanwhile).
    """

    REFRESH_PERIOD_S = 5.0
    CHANNELS_PER_REPLICA = 4
    DRAINING_TTL_S = 30.0

    def __init__(self, handle, auth_key: bytes):
        self._handle = handle
        self._auth = auth_key
        self._lock = threading.Lock()
        # actor_id hex -> {"addr", "channels": [DirectChannel], "rr": int}
        self._replicas: Dict[str, dict] = {}
        self._outstanding: Dict[str, int] = {}
        # rid -> monotonic timestamp of the drain rejection: the replica is
        # alive but refusing work; skip it until the handle-info refresh
        # drops it (TTL-bounded so a cancelled drain re-enters the pool)
        self._draining: Dict[str, float] = {}
        self._last_refresh = 0.0
        self.refresh()

    def refresh(self) -> None:
        import time

        import ray_tpu

        with self._lock:
            if time.monotonic() - self._last_refresh < 1.0:
                return
            self._last_refresh = time.monotonic()
        try:
            self._handle._maybe_refresh()  # pick up autoscaling changes
        except Exception:
            pass
        with self._lock:
            replicas = list(getattr(self._handle, "_replicas", []) or [])
        addrs: Dict[str, Any] = {}
        for r in replicas:
            rid = r._actor_id.hex()
            with self._lock:
                if rid in self._replicas:
                    continue
            try:
                addrs[rid] = (r, ray_tpu.get(r.direct_address.remote(), timeout=30))
            except Exception:
                continue
        for rid, (r, addr) in addrs.items():
            if not addr:
                continue
            try:
                chans = [
                    DirectChannel(addr, self._auth)
                    for _ in range(self.CHANNELS_PER_REPLICA)
                ]
            except Exception:
                continue
            with self._lock:
                self._replicas[rid] = {"addr": addr, "channels": chans, "rr": 0}
                self._outstanding.setdefault(rid, 0)
        # drop replicas no longer in the handle's set
        live = {r._actor_id.hex() for r in replicas}
        with self._lock:
            for rid in [x for x in self._replicas if x not in live]:
                for c in self._replicas[rid]["channels"]:
                    c.close()
                del self._replicas[rid]
                self._outstanding.pop(rid, None)
            now = time.monotonic()
            for rid in [
                r
                for r, ts in self._draining.items()
                if r not in self._replicas or now - ts > self.DRAINING_TTL_S
            ]:
                del self._draining[rid]

    def _mark_draining(self, rid: str) -> None:
        import time

        with self._lock:
            if rid in self._replicas:
                self._draining[rid] = time.monotonic()

    def total_outstanding(self) -> int:
        """In-flight direct-path requests (admission-control input)."""
        with self._lock:
            return sum(self._outstanding.values())

    def _pick(self) -> Optional[Tuple[str, DirectChannel]]:
        import random

        with self._lock:
            rids = [r for r in self._replicas if r not in self._draining]
            if not rids:
                return None
            if len(rids) == 1:
                rid = rids[0]
            else:
                a, b = random.sample(rids, 2)
                rid = a if self._outstanding.get(a, 0) <= self._outstanding.get(b, 0) else b
            entry = self._replicas[rid]
            entry["rr"] = (entry["rr"] + 1) % len(entry["channels"])
            chan = entry["channels"][entry["rr"]]
            if chan.broken:
                # lazy re-dial into the same slot (a stream abandoned on it)
                try:
                    chan = DirectChannel(entry["addr"], self._auth)
                    entry["channels"][entry["rr"]] = chan
                except Exception:
                    return None
            self._outstanding[rid] = self._outstanding.get(rid, 0) + 1
            return rid, chan

    def _done(self, rid: str) -> None:
        with self._lock:
            if rid in self._outstanding:
                self._outstanding[rid] -= 1

    def _evict(self, rid: str) -> None:
        with self._lock:
            entry = self._replicas.pop(rid, None)
            self._outstanding.pop(rid, None)
        if entry:
            for c in entry["channels"]:
                c.close()

    def call(self, method: str, args, kwargs, model_id: str = "", timeout=None):
        """Direct call; raises _DirectUnavailable when no channel works (the
        caller falls back to the handle path). A channel that breaks AFTER
        the replica's started-marker is torn work: surfaced as a typed
        ReplicaDiedError, never silently re-executed."""
        import time

        from ray_tpu.serve.exceptions import ReplicaDiedError, ReplicaDrainingError

        if time.monotonic() - self._last_refresh > self.REFRESH_PERIOD_S:
            self.refresh()
        for _ in range(3):
            picked = self._pick()
            if picked is None:
                break
            rid, chan = picked
            try:
                try:
                    return chan.call(method, args, kwargs, model_id, timeout=timeout)
                finally:
                    self._done(rid)
            except ReplicaDrainingError:
                # replica alive but refusing new work: request never started,
                # retry on another replica immediately
                self._mark_draining(rid)
            except _ChannelBroken as e:
                self._evict(rid)
                if getattr(e, "timed_out", False):
                    # slow request, not a dead replica: typed timeout (the
                    # proxy maps it to 504). The channel itself is gone —
                    # its framing can't be trusted — but the replica
                    # re-enters the pool on the next refresh.
                    from ray_tpu.serve.exceptions import RequestTimeoutError

                    raise RequestTimeoutError(
                        getattr(self._handle, "deployment_name", ""),
                        method,
                        timeout or DirectChannel.CALL_TIMEOUT_S,
                    ) from e
                if getattr(e, "started", False):
                    raise ReplicaDiedError(
                        deployment=getattr(self._handle, "deployment_name", ""),
                        app=getattr(self._handle, "app_name", ""),
                        method=method,
                        replica_id=rid,
                        started=True,
                        reason=str(e),
                    ) from e
        raise _DirectUnavailable()

    def call_streaming(self, method: str, args, kwargs, model_id: str = ""):
        from ray_tpu.serve.exceptions import ReplicaDiedError, ReplicaDrainingError

        for _ in range(3):
            picked = self._pick()
            if picked is None:
                raise _DirectUnavailable()
            rid, chan = picked
            try:
                try:
                    yield from chan.call_streaming(method, args, kwargs, model_id)
                    return
                finally:
                    self._done(rid)
            except ReplicaDrainingError:
                self._mark_draining(rid)  # nothing sent: pick another replica
            except _ChannelBroken as e:
                self._evict(rid)
                if getattr(e, "timed_out", False):
                    from ray_tpu.serve.exceptions import RequestTimeoutError

                    raise RequestTimeoutError(
                        getattr(self._handle, "deployment_name", ""),
                        method,
                        DirectChannel.STREAM_FRAME_TIMEOUT_S,
                    ) from e
                if getattr(e, "started", False) or getattr(e, "items_sent", 0):
                    # the stream had begun (possibly with chunks already
                    # relayed to the client): typed torn-stream error
                    raise ReplicaDiedError(
                        deployment=getattr(self._handle, "deployment_name", ""),
                        app=getattr(self._handle, "app_name", ""),
                        method=method,
                        replica_id=rid,
                        started=True,
                        reason=str(e),
                    ) from e
                raise _DirectUnavailable()
        raise _DirectUnavailable()

    def open_dedicated(self):
        """Dial a FRESH connection to one replica for a long-lived
        bidirectional session (websocket). Not pooled — the caller owns and
        closes it; the replica dedicates its serving thread to the session.
        Raises _DirectUnavailable when no replica answers."""
        import random
        import time

        if time.monotonic() - self._last_refresh > self.REFRESH_PERIOD_S:
            self.refresh()
        with self._lock:
            addrs = [
                e["addr"]
                for rid, e in self._replicas.items()
                if rid not in self._draining
            ]
        random.shuffle(addrs)
        from ray_tpu._private.object_transfer import _dial

        for addr in addrs:
            try:
                return _dial(addr, self._auth)
            except Exception:
                continue
        raise _DirectUnavailable()

    def close(self):
        with self._lock:
            entries = list(self._replicas.values())
            self._replicas.clear()
        for entry in entries:
            for c in entry["channels"]:
                c.close()


class _ChannelBroken(Exception):
    """Transport-level failure on a direct channel (distinct from user
    exceptions, which may themselves subclass OSError). ``started`` /
    ``items_sent`` carry the replica's started-marker state at the break."""

    started: bool = False
    items_sent: int = 0


class _DirectUnavailable(Exception):
    pass
