"""CLI: ``python -m ray_tpu <command>``.

Parity: ``python/ray/scripts/scripts.py`` (``ray start/stop/status``,
``ray job submit/status/logs/stop/list``, ``ray summary``, ``ray timeline``,
``ray memory``). Cluster-lifecycle commands operate on a head started in this
process (``start --block``) since the transport is in-process for now.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _init(args):
    import ray_tpu

    return ray_tpu.init(
        num_cpus=getattr(args, "num_cpus", None),
        num_tpus=getattr(args, "num_tpus", None),
        ignore_reinit_error=True,
    )


def cmd_start(args):
    import ray_tpu

    if args.address:
        # worker node: run the node daemon attached to the head
        # (parity: `ray start --address`; blocks like the raylet).
        # --node-host is this machine's address as seen by its peers (the
        # object server binds and advertises it).
        from ray_tpu._private import raylet

        raylet.main(
            [
                "--address",
                args.address,
                "--num-cpus",
                str(args.num_cpus or 1),
                "--num-tpus",
                str(args.num_tpus or 0),
                "--host",
                args.node_host,
            ]
        )
        return

    rt = _init(args)
    if args.head:
        import socket

        host, port = rt.node.start_head_server()
        adv = host
        if host == "0.0.0.0":
            try:
                adv = socket.gethostbyname(socket.gethostname())
            except OSError:
                adv = socket.getfqdn()
        print(f"head listening on {host}:{port}")
        print(f"  auth key (export RAY_TPU_AUTH=...): {rt.config.cluster_auth_key}")
        print(f"  join:    python -m ray_tpu start --address {adv}:{port} "
              f"--node-host <this-machine-ip>")
        print(f"  connect: ray_tpu.init(address='{adv}:{port}')  # head machine only")
    print(f"ray_tpu head started. resources: {ray_tpu.cluster_resources()}")
    if args.block or args.head:
        print("blocking; Ctrl-C to stop")
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
        ray_tpu.shutdown()


def cmd_status(args):
    import ray_tpu

    _init(args)
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    print("== cluster resources ==")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0):.1f} / {total[k]:.1f} available")
    from ray_tpu.util import state

    nodes = state.list_nodes()
    print(f"== nodes ({len(nodes)}) ==")
    for n in nodes:
        print(f"  {n['node_id'][:12]} alive={n['alive']} total={n['total']}")
    if getattr(args, "backlog", False):
        summary = state.backlog_summary()
        rows = sorted(
            summary.get("shapes", ()),
            key=lambda r: -(r.get("queued", 0) + r.get("node_backlog", 0)),
        )
        print(f"== scheduler backlog by shape ({len(rows)}) ==")
        for row in rows:
            shape_s = (
                ",".join(
                    f"{k}:{v:g}" for k, v in sorted(row["shape"].items())
                )
                or "<none>"
            )
            print(
                f"  {shape_s:<40} queued={row['queued']:<8} "
                f"leased={row['leased']:<8} node_backlog={row['node_backlog']}"
            )
        if not rows:
            print("  (empty)")
        pg = summary.get("pg_pending", ())
        if pg:
            print(f"== pending placement-group bundles ({len(pg)}) ==")
            for b in pg[:20]:
                print("  " + ",".join(f"{k}:{v:g}" for k, v in sorted(b.items())))


def cmd_summary(args):
    import ray_tpu
    from ray_tpu.util import state

    _init(args)
    print(json.dumps(state.summarize_tasks(), indent=2))


_MEM_UNITS = {"B": 1, "KB": 1e3, "MB": 1e6, "GB": 1e9}


def _fmt_bytes(n, units: str) -> str:
    div = _MEM_UNITS[units]
    return f"{n}" if units == "B" else f"{n / div:.1f}"


def _print_kv_cache_section(units: str) -> None:
    """Paged KV-cache occupancy per LLM deployment (the live shed
    signal), folded from the aggregated ``ray_tpu_kv_*`` gauges."""
    try:
        from ray_tpu.util.metrics import prometheus_text

        series: dict = {}
        for line in prometheus_text().splitlines():
            if not line.startswith("ray_tpu_kv_"):
                continue
            name, _, value = line.rpartition(" ")
            dep = "?"
            if 'deployment="' in name:
                dep = name.split('deployment="', 1)[1].split('"', 1)[0]
            metric = name.split("{", 1)[0]
            series.setdefault(dep, {})[metric] = float(value)
        if not series:
            return
        print("== paged KV cache (LLM serving plane) ==")
        for dep, vals in sorted(series.items()):
            total = vals.get("ray_tpu_kv_blocks_total", 0)
            free = vals.get("ray_tpu_kv_blocks_free", 0)
            occ = vals.get("ray_tpu_kv_occupancy_ratio", 0.0)
            nbytes = vals.get("ray_tpu_kv_pool_bytes", 0)
            print(
                f"  {dep}: {total - free:g}/{total:g} blocks in use "
                f"({occ:.0%} occupancy, pool "
                f"{_fmt_bytes(int(nbytes), units)} {units})"
            )
    except Exception:
        pass  # KV gauges are best-effort decoration on the memory view


def cmd_memory(args):
    """Memory plane: live objects grouped by creation callsite (or job /
    node / ungrouped) with owner, bytes, and leak classification — the
    ``ray memory`` parity surface for "where did the bytes go"."""
    from ray_tpu.util import state

    _init(args)
    units = args.units
    if args.group_by == "object":
        page = state.list_objects_page(limit=args.limit)
        rows = page["rows"]
        if args.leaks_only:
            rows = [r for r in rows if r.get("class") == "LEAK_SUSPECT"]
        rows.sort(key=lambda r: -r["size_bytes"])
        if args.json:
            page["rows"] = rows  # --leaks-only + sort apply to JSON too
            print(json.dumps(page, indent=2, default=str))
            return
        total = sum(r["size_bytes"] for r in rows)
        print(
            f"{len(rows)} objects, {_fmt_bytes(total, units)} {units} live"
            + ("  [TRUNCATED]" if page.get("truncated") else "")
        )
        print(
            f"{'BYTES(' + units + ')':>12} {'REFS':>5} {'CLASS':<20} "
            f"{'JOB':<10} {'KIND':<12} {'OBJECT':<18} CALLSITE"
        )
        for r in rows:
            print(
                f"{_fmt_bytes(r['size_bytes'], units):>12} "
                f"{r['ref_count']:>5} {r.get('class') or '-':<20} "
                f"{r.get('job') or '-':<10} {r.get('kind') or '-':<12} "
                f"{r['object_id'][:16]:<18} {r.get('callsite') or '-'}"
            )
        _print_kv_cache_section(units)
        return
    summary = state.summarize_objects(group_by=args.group_by, limit=args.limit)
    rows = summary["rows"]
    if args.leaks_only:
        rows = [r for r in rows if r.get("leak_suspect")]
    if args.json:
        summary["rows"] = rows
        print(json.dumps(summary, indent=2, default=str))
        return
    store = summary.get("store") or {}
    print(
        f"== object store: {summary['total_objects']} live objects, "
        f"{_fmt_bytes(summary['total_bytes'], units)} {units} "
        f"(sealed {_fmt_bytes(store.get('sealed_bytes', 0), units)} / "
        f"unsealed {_fmt_bytes(store.get('unsealed_bytes', 0), units)} / "
        f"capacity {_fmt_bytes(store.get('capacity_bytes', 0), units)} / "
        f"high-water {_fmt_bytes(store.get('highwater_bytes', 0), units)} "
        f"{units}) =="
    )
    print(
        f"{'BYTES(' + units + ')':>12} {'COUNT':>6} {'LEAK':<5} "
        f"{'CLASSES':<28} {args.group_by.upper()}"
    )
    for g in rows:
        classes = ",".join(
            f"{c}:{n}" for c, n in sorted(g.get("classes", {}).items())
        )
        print(
            f"{_fmt_bytes(g['bytes'], units):>12} {g['count']:>6} "
            f"{'YES' if g.get('leak_suspect') else '-':<5} "
            f"{classes:<28} {g['group']}"
        )
    if summary.get("truncated"):
        print(f"  ... truncated at {args.limit} groups")
    suspects = summary.get("leak_suspects") or {}
    if suspects:
        print(f"== leak suspects ({len(suspects)}) ==")
        for cs, info in sorted(
            suspects.items(), key=lambda kv: -kv[1]["live_bytes"]
        ):
            print(
                f"  {cs}: {info['live_count']} objects, "
                f"{_fmt_bytes(info['live_bytes'], units)} {units} "
                f"(+{_fmt_bytes(info['growth_bytes'], units)} over "
                f"{info['window_s']:g}s)  exemplars: "
                + ",".join(
                    o[:16] for o in info.get("exemplar_object_ids", [])[:3]
                )
            )
    elif args.leaks_only and not rows:
        print("no leak suspects")
    _print_kv_cache_section(units)


def _parse_since(raw: str) -> float:
    """``--since`` value -> wall timestamp: a duration suffixed s/m/h/d
    (``10m`` = 10 minutes ago), a bare number of seconds ago, or an
    absolute unix timestamp (values > 1e9)."""
    import time as _time

    raw = raw.strip()
    mult = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}.get(raw[-1:])
    if mult is not None:
        return _time.time() - float(raw[:-1]) * mult
    v = float(raw)
    return v if v > 1e9 else _time.time() - v


def _print_event(ev: dict) -> None:
    import time as _time

    stamp = _time.strftime(
        "%Y-%m-%d %H:%M:%S", _time.localtime(ev.get("time", 0))
    )
    where = " ".join(
        f"{k}={ev[k]}"
        for k in ("task_id", "node_id", "pid", "attempt")
        if ev.get(k) is not None
    )
    print(
        f"{stamp} {ev.get('severity', 'INFO'):<7} "
        f"{ev.get('type', '?'):<16} [{ev.get('source', '?')}] "
        f"{ev.get('message', '')}" + (f"  ({where})" if where else "")
    )


def cmd_events(args):
    """Cluster event log (failure forensics): WORKER_DIED, TASK_FAILED,
    STRAGGLER, OOM, ... with severity/source/provenance. ``--follow``
    tails the log via the server-side ``after_event_id`` cursor (only
    events beyond the last-seen id cross the wire per poll)."""
    import time as _time

    from ray_tpu.util import state

    _init(args)
    filters = []
    if args.severity:
        filters.append(("severity", "=", args.severity.upper()))
    if args.type:
        filters.append(("type", "=", args.type.upper()))
    since_ts = _parse_since(args.since) if args.since else None
    rows = state.list_cluster_events(
        filters=filters or None,
        limit=args.limit,
        job_id=args.job_id or None,
        since_ts=since_ts,
    )
    if args.json and not args.follow:
        print(json.dumps(rows, indent=2, default=str))
        return
    for ev in rows:
        print(json.dumps(ev, default=str)) if args.json else _print_event(ev)
    if not rows and not args.follow:
        print("no cluster events recorded")
        return
    if not args.follow:
        return
    cursor = max((ev.get("event_id", 0) for ev in rows), default=0)
    try:
        while True:
            _time.sleep(1.0)
            fresh = state.list_cluster_events(
                filters=filters or None,
                limit=args.limit,
                job_id=args.job_id or None,
                after_event_id=cursor,
            )
            for ev in fresh:
                cursor = max(cursor, ev.get("event_id", 0))
                (print(json.dumps(ev, default=str)) if args.json
                 else _print_event(ev))
    except KeyboardInterrupt:
        return


def cmd_doctor(args):
    """One-shot cluster health digest: open incidents (with verdicts as
    they close), SLO burn status, top anomaly counters, store snapshot."""
    from ray_tpu.util import state

    _init(args)
    d = state.doctor()
    if args.json:
        print(json.dumps(d, indent=2, default=str))
        return
    if d.get("error"):
        print(f"doctor: {d['error']}")
        return
    verdict = "HEALTHY" if d.get("healthy") else "ATTENTION NEEDED"
    print(f"== cluster health: {verdict} ==")
    print(
        f"  nodes: {d.get('nodes', '?')}  workers: {d.get('workers', '?')}"
    )
    store = d.get("store") or {}
    if store.get("store_capacity_bytes"):
        used = store.get("store_used_bytes", 0) or 0
        cap = store["store_capacity_bytes"]
        print(
            f"  object store: {used / 2**20:.1f} / {cap / 2**20:.0f} MiB "
            f"({100.0 * used / cap:.1f}%)"
        )
    open_rows = d.get("open_incidents") or []
    print(f"== open incidents ({len(open_rows)}) ==")
    for row in open_rows:
        print(
            f"  {row['id']:<8} {row['kind']:<22} {row['subject']:<28} "
            f"x{row['count']}  planes={','.join(row.get('planes') or [])}"
        )
    closed = d.get("recently_closed") or []
    if closed:
        print(f"== recently closed ({len(closed)}) ==")
        for row in closed:
            print(
                f"  {row['id']:<8} {row['kind']:<22} "
                f"{row['duration_s'] or 0:.1f}s  {row.get('verdict') or ''}"
            )
    slos = d.get("slos") or []
    print(f"== SLOs ({len(slos)}) ==")
    for s in slos:
        worst = s.get("worst") or {}
        status = "OK" if s.get("ok") else "BREACHED"
        burns = (
            f"burn fast={worst.get('burn_fast')} slow={worst.get('burn_slow')}"
            if worst
            else "no data"
        )
        print(
            f"  {s['name']:<24} {s['kind']:<26} {status:<9} "
            f"target={s['target']:g}  {burns}"
        )
    wd = d.get("watchdogs") or {}
    anomalies = {k: v for k, v in wd.items() if v}
    if anomalies:
        print(
            "== watchdog totals == "
            + "  ".join(f"{k}={v}" for k, v in sorted(anomalies.items()))
        )
    top = d.get("event_counts") or {}
    if top:
        print(
            "== top events == "
            + "  ".join(f"{k}={v}" for k, v in list(top.items())[:8])
        )


def cmd_incidents(args):
    """Incident records: `incidents` lists them, `incidents show <id>`
    prints one record's cross-plane digest."""
    import time as _time

    from ray_tpu.util import state

    _init(args)
    parts = list(args.incident_id or [])
    if parts and parts[0] == "show":
        parts = parts[1:]
    incident_id = parts[0] if parts else None
    if incident_id:
        inc = state.get_incident(incident_id)
        if inc is None:
            print(f"no incident {incident_id}")
            sys.exit(1)
        if args.json:
            print(json.dumps(inc, indent=2, default=str))
            return
        stamp = _time.strftime(
            "%Y-%m-%d %H:%M:%S", _time.localtime(inc["opened_at"])
        )
        print(
            f"{inc['id']} [{inc['kind']}] {inc['subject']}  "
            f"state={inc['state']} severity={inc['severity']} "
            f"source={inc['source']} opened={stamp} "
            f"triggers={inc['count']}"
        )
        if inc.get("duration_s") is not None:
            print(f"  duration: {inc['duration_s']:.1f}s")
        if inc.get("verdict"):
            print(f"  verdict: {inc['verdict']}")
        digest = inc.get("digest") or {}
        print(f"  planes joined: {', '.join(digest.get('planes') or [])}")
        for tr in digest.get("traces") or []:
            stages = ", ".join(
                f"{k}={v}ms"
                for k, v in sorted(
                    (tr.get("stages") or {}).items(),
                    key=lambda kv: -(kv[1] or 0),
                )[:4]
            )
            print(
                f"  trace {tr['trace_id'][:16]}: "
                f"{tr.get('duration_ms')}ms over {tr.get('spans')} spans "
                f"({stages})"
            )
        mem = digest.get("memory") or {}
        for cs in (mem.get("top_callsites") or [])[:3]:
            print(
                f"  mem top: {cs.get('callsite')} = {cs.get('bytes')}B "
                f"({cs.get('count')} objects)"
            )
        net = digest.get("net") or {}
        for row in net.get("links") or []:
            print(
                f"  link {row['src']}->{row['dst']} ({row['path']}): "
                f"{row.get('ewma_gib_per_s')} GiB/s, "
                f"{row.get('stalls')} stalls, slow={row.get('slow')}"
            )
        if digest.get("train"):
            t = digest["train"]
            print(
                f"  train run {t.get('run')}: goodput={t.get('goodput')} "
                f"downtime={t.get('downtime_s')}s "
                f"recompiles={t.get('recompiles')}"
            )
        ctl = digest.get("control") or {}
        if ctl:
            print(
                f"  control: {len(ctl.get('decisions') or [])} decisions, "
                f"{len(ctl.get('launches') or [])} launches, "
                f"spawn_fail_streaks={ctl.get('spawn_fail_streaks') or {}}"
            )
        for ev in (inc.get("events") or [])[-5:]:
            _print_event(ev)
        return
    rows = state.list_incidents(
        limit=args.limit,
        state=args.state or None,
        kind=args.type.upper() if args.type else None,
    )
    if args.json:
        print(json.dumps(rows, indent=2, default=str))
        return
    if not rows:
        print("no incidents recorded")
        return
    for row in rows:
        stamp = _time.strftime(
            "%H:%M:%S", _time.localtime(row["opened_at"])
        )
        dur = (
            f"{row['duration_s']:.0f}s"
            if row.get("duration_s") is not None
            else "open"
        )
        print(
            f"{row['id']:<8} {stamp} {row['state']:<7} {row['kind']:<22} "
            f"{row['subject']:<28} x{row['count']:<3} {dur:<6} "
            f"{row.get('verdict') or ''}"
        )


def cmd_actors(args):
    """Actor fleet view (control-plane observability): one row per actor
    with its launch lifecycle stage; ``--pending`` narrows to creations
    still in flight and shows the stage each is blocked in;
    ``launch-profile`` prints the per-stage launch-latency decomposition
    (the ROADMAP item-2 'where does the 75ms/actor go' baseline)."""
    import time as _time

    from ray_tpu.util import state

    _init(args)
    if args.actors_cmd == "launch-profile":
        prof = state.launch_profile(limit=args.limit)
        if args.json:
            print(json.dumps(prof, indent=2, default=str))
            return
        total = prof.get("total") or {}
        print(
            f"actor launches: {prof.get('launched_total', 0)} total, "
            f"{prof.get('window', 0)} in window  "
            f"(total mean={total.get('mean_ms', 0):g}ms "
            f"p95={total.get('p95_ms', 0):g}ms)"
        )
        stages = prof.get("stages") or {}
        if not stages:
            print("no completed actor launches recorded")
            return
        print(
            f"  {'stage':<22} {'count':>6} {'mean':>10} "
            f"{'p50':>10} {'p95':>10} {'max':>10}"
        )
        for name, row in stages.items():
            print(
                f"  {name.replace('_ms', ''):<22} {row['count']:>6} "
                f"{row['mean_ms']:>8.1f}ms {row['p50_ms']:>8.1f}ms "
                f"{row['p95_ms']:>8.1f}ms {row['max_ms']:>8.1f}ms"
            )
        boot = prof.get("worker_boot_stage_seconds") or {}
        if boot:
            print(
                "worker boot (cumulative): "
                + "  ".join(
                    f"{k.replace('_ms', '')}={v:g}s"
                    for k, v in boot.items()
                )
            )
        return
    rows = state.list_actors(limit=args.limit)
    if args.pending:
        rows = [r for r in rows if r.get("state") == "PENDING"]
    if args.json:
        print(json.dumps(rows, indent=2, default=str))
        return
    now = _time.time()
    for r in rows:
        stage = r.get("launch_stage") or "?"
        line = (
            f"{r['actor_id'][:16]}  {r.get('state', '?'):<10} "
            f"stage={stage:<10} "
            f"{(r.get('class_name') or r.get('name') or '-'):<24}"
        )
        if r.get("node_id"):
            line += f"  node={r['node_id'][:8]}"
        if args.pending:
            # how long the creation has been stuck in its current stage
            ts = (r.get("stage_ts") or {}).get(stage)
            if ts:
                line += f"  blocked {now - ts:.1f}s in {stage}"
            if r.get("trace_id"):
                line += f"  trace={r['trace_id']}"
        elif r.get("lifecycle_ms"):
            lc = r["lifecycle_ms"]
            line += "  [" + "  ".join(
                f"{k.replace('_ms', '')}={v:g}ms"
                for k, v in lc.items()
                if k != "total_ms"
            ) + f"]  total={lc.get('total_ms', 0):g}ms"
        print(line)
    if not rows:
        print("no pending actor creations" if args.pending else "no actors")


def cmd_decisions(args):
    """Decision flight recorder: the bounded ring of scheduler placement
    decisions and autoscaler reconcile decisions, oldest first — why each
    actor landed where it did, and why the fleet did (or didn't) scale."""
    import time as _time

    from ray_tpu.util import state

    _init(args)
    rows = state.list_decisions(limit=args.limit, kind=args.kind or "")
    if args.json:
        print(json.dumps(rows, indent=2, default=str))
        return
    for d in rows:
        stamp = _time.strftime(
            "%H:%M:%S", _time.localtime(d.get("t", 0))
        )
        rest = " ".join(
            f"{k}={d[k]}"
            for k in sorted(d)
            if k not in ("seq", "t", "kind") and d[k] is not None
        )
        print(f"#{d.get('seq', '?'):<6} {stamp} {d.get('kind', '?'):<11} {rest}")
    if not rows:
        print("no decisions recorded")


def cmd_ckpt(args):
    """Checkpoint plane: list/inspect/verify/GC committed checkpoints
    (``ray_tpu.train.checkpointing``). With ``--storage`` the commands work
    directly against a path or URI (no cluster needed); without it,
    ``list``/``latest`` read the cluster's KV run registry."""
    import time as _time

    from ray_tpu.train import checkpointing

    def _fmt_row(row):
        created = row.get("created")
        stamp = (
            _time.strftime("%Y-%m-%d %H:%M:%S", _time.localtime(created))
            if created
            else "-"
        )
        size = row.get("size_bytes")
        size_s = f"{size / 1e6:.1f}MB" if size is not None else "-"
        return (
            f"{row.get('run') or '-':<24} step={row['step']:<8} "
            f"{'COMMITTED' if row['committed'] else 'uncommitted':<12} "
            f"{size_s:>10}  {stamp}  {row['path']}"
        )

    if args.ckpt_cmd == "list":
        if args.storage:
            rows = checkpointing.list_checkpoints(args.storage)
        else:
            from ray_tpu.util import state

            _init(args)
            rows = state.list_checkpoints(limit=args.limit)
        rows = rows[: args.limit]
        if args.json:
            print(json.dumps(rows, indent=2, default=str))
            return
        for row in rows:
            print(_fmt_row(row))
        if not rows:
            print("no checkpoints found")
    elif args.ckpt_cmd == "latest":
        if args.storage:
            step = checkpointing.latest_step(args.storage)
            if step is None:
                print("no committed checkpoint")
                sys.exit(1)
            print(checkpointing.discover_steps(args.storage)[step])
        else:
            from ray_tpu.util import state

            _init(args)
            rows = [r for r in state.list_checkpoints() if r["committed"]]
            if not rows:
                print("no committed checkpoint")
                sys.exit(1)
            # newest across ALL runs — the rows come back sorted per run
            print(_fmt_row(max(rows, key=lambda r: r.get("created") or 0)))
    elif args.ckpt_cmd == "verify":
        from ray_tpu._private.external_storage import IntegrityError

        try:
            manifest = checkpointing.verify_checkpoint(args.prefix)
        except IntegrityError as e:
            print(f"FAILED: {e}")
            sys.exit(1)
        files = manifest.get("files", {})
        print(
            f"OK: {len(files)} files, "
            f"{sum(e['size'] for e in files.values())} bytes, "
            f"step={manifest.get('step')} world_size={manifest.get('world_size')}"
        )
    elif args.ckpt_cmd == "gc":
        deleted = checkpointing.gc_checkpoints(
            args.storage, keep=args.keep, max_age_s=args.max_age_s
        )
        print(f"deleted {len(deleted)} checkpoint(s): {deleted}")
        if args.clear_cache:
            n = checkpointing.clear_restore_cache()
            print(f"cleared {n} restore-cache entr{'y' if n == 1 else 'ies'}")


def cmd_timeline(args):
    import ray_tpu
    from ray_tpu.util import state

    _init(args)
    out = args.output or "timeline.json"
    events = ray_tpu.timeline(filename=out)
    print(f"wrote {len(events)} events to {out} (chrome://tracing)")
    # summarize_tasks-backed digest so the trace has headline numbers
    summary = state.summarize_tasks()
    if summary:
        print("task summary (name: state counts):")
        for name, counts in sorted(summary.items()):
            states = " ".join(f"{s}={n}" for s, n in sorted(counts.items()))
            print(f"  {name}: {states}")


def cmd_trace(args):
    """Request-tracing plane: reconstruct one request's cross-process span
    tree and print its critical-path latency decomposition (submit ->
    queue_wait -> dispatch -> arg_fetch -> execute -> result_put ->
    stream_yield; TTFT for streaming serve requests)."""
    import ray_tpu

    _init(args)
    if args.list or not args.trace_id:
        rows = ray_tpu.recent_traces(limit=args.limit)
        if args.json:
            print(json.dumps(rows, indent=2))
            return
        if not rows:
            print("no traces recorded (is tracing_enabled on?)")
            return
        print(f"{'trace_id':34} {'root':24} {'events':>6}  age")
        now = time.time()
        for r in rows:
            age = now - (r.get("last_time") or now)
            print(
                f"{r['trace_id']:34} {str(r.get('root'))[:24]:24} "
                f"{r.get('events', 0):>6}  {age:.1f}s ago"
            )
        return
    t = ray_tpu.trace(args.trace_id)
    if not t.span_count():
        print(f"no events recorded for trace {args.trace_id}")
        return
    if args.json:
        print(json.dumps(t.to_dict(), indent=2, default=str))
    else:
        print(t.summary())
    if args.flame:
        fmt = "collapsed" if args.flame.endswith(".txt") else "speedscope"
        n = ray_tpu.profile_dump(
            args.flame, format=fmt, trace_id=args.trace_id
        )
        print(f"wrote {fmt} flame graph ({n} profiles/lines) to {args.flame}")


def cmd_train(args):
    """Training step plane: per-run step-time attribution ("where did the
    step go") — run digests, per-rank step waterfalls with stage
    decomposition + straggler marks, and ingest-stall / downtime views."""
    import ray_tpu
    from ray_tpu.util import state

    _init(args)
    sub = args.train_cmd
    if sub == "runs":
        rows = state.list_train_runs()
        if args.json:
            print(json.dumps(rows, indent=2, default=str))
            return
        if not rows:
            print("no training runs recorded (is train_obs_enabled on?)")
            return
        print(
            f"{'run':28} {'world':>5} {'steps':>6} {'recomp':>6} "
            f"{'goodput':>8} {'downtime':>9} {'data_wait':>9} "
            f"{'skew_ms':>8}  status"
        )
        for r in rows:
            gp = r.get("goodput")
            dw = r.get("data_wait_ratio")
            gp_s = "?" if gp is None else f"{gp:.3f}"
            dw_s = "?" if dw is None else f"{dw:.1%}"
            print(
                f"{str(r.get('run'))[:28]:28} {r.get('world', 0):>5} "
                f"{r.get('steps', 0):>6} {r.get('recompiles', 0):>6} "
                f"{gp_s:>8} "
                f"{r.get('downtime_s') or 0:>8.1f}s "
                f"{dw_s:>9} "
                f"{r.get('max_skew_ms') or 0:>8.1f}  {r.get('status', '?')}"
            )
        return
    if not args.run:
        raise SystemExit(f"`ray_tpu train {sub}` needs --run <name>")
    t = ray_tpu.train_timeline(args.run, max_steps=args.limit)
    if not t.to_dict():
        print(f"no step records for run {args.run!r}")
        return
    if sub == "steps":
        d = t.to_dict()
        if args.rank is not None:
            # keep only the requested rank's records in every step row
            for srec in d.get("steps") or []:
                srec["ranks"] = {
                    r: rec
                    for r, rec in (srec.get("ranks") or {}).items()
                    if int(r) == args.rank
                }
            d["steps"] = [s for s in d["steps"] if s["ranks"]]
            t = type(t)(d)
        if args.json:
            print(json.dumps(t.to_dict(), indent=2, default=str))
        else:
            print(t.summary(max_steps=args.limit or 20))
        return
    if sub == "stalls":
        d = t.to_dict()
        body = {
            "run": d.get("run"),
            "ingest_stalls_by_operator_ms": d.get("ops") or {},
            "stage_shares": t.stage_shares(),
            "downtime_ledger": (d.get("meta") or {}).get("downtime_ledger")
            or [],
            "skew": d.get("skew") or {},
        }
        if args.json:
            print(json.dumps(body, indent=2, default=str))
            return
        print(f"run {body['run']} — where did the step go")
        shares = body["stage_shares"]
        if shares:
            for k, v in sorted(shares.items(), key=lambda kv: -kv[1]):
                print(f"  {k:<18} {v * 100:6.1f}%")
        ops = body["ingest_stalls_by_operator_ms"]
        if ops:
            print("ingest stalls by operator:")
            for op, ms in sorted(ops.items(), key=lambda kv: -kv[1]):
                print(f"  {op:<24} {ms:10.1f}ms")
        ledger = body["downtime_ledger"]
        if ledger:
            total = sum(e.get("seconds", 0.0) for e in ledger)
            print(f"downtime ledger ({total:.2f}s attributed):")
            for e in ledger:
                print(
                    f"  {e.get('cause', '?'):<18} {e.get('seconds', 0):8.2f}s"
                    f"  {e.get('detail', '')}"
                )
        return
    raise SystemExit(f"unknown train subcommand {sub!r}")


def cmd_net(args):
    """Transfer plane ("where did the wire go"): per-link ledger, recent
    transfer stage decompositions, and heaviest-traffic groupings."""
    from ray_tpu.util import state

    _init(args)
    sub = args.net_cmd
    if sub == "links":
        rows = state.list_links(limit=args.limit)
        if args.json:
            print(json.dumps(rows, indent=2, default=str))
            return
        if not rows:
            print("no transfers recorded (is transfer_plane_enabled on?)")
            return
        print(
            f"{'SRC':<14} {'DST':<14} {'PATH':<9} {'MB':>10} {'XFERS':>6} "
            f"{'FAIL':>5} {'STALL':>5} {'INFL':>5} {'GiB/s':>8} "
            f"{'HOP':>4}  FLAGS"
        )
        for r in rows:
            ew = r.get("ewma_gib_per_s")
            print(
                f"{r['src']:<14} {r['dst']:<14} {r['path']:<9} "
                f"{r['bytes'] / 1e6:>10.1f} {r['transfers']:>6} "
                f"{r['failures']:>5} {r['stalls']:>5} "
                f"{r.get('inflight', 0):>5} "
                f"{'?' if ew is None else f'{ew:.4f}':>8} "
                f"{r.get('max_hop', 0):>4}  "
                f"{'SLOW' if r.get('slow') else '-'}"
            )
        return
    if sub == "transfers":
        rows = state.list_transfers(limit=args.limit)
        if args.json:
            print(json.dumps(rows, indent=2, default=str))
            return
        if not rows:
            print("no transfers recorded (is transfer_plane_enabled on?)")
            return
        print(
            f"{'OBJECT':<18} {'LINK':<26} {'PATH':<9} {'MB':>8} "
            f"{'GiB/s':>8} {'HOP':>4} {'OK':<4} STAGES"
        )
        for r in rows:
            stages = "  ".join(
                f"{k.replace('_ms', '')}={v:g}ms"
                for k, v in (r.get("stages_ms") or {}).items()
            )
            gp = r.get("gib_per_s")
            print(
                f"{r['object_id'][:16]:<18} "
                f"{r['src'] + '->' + r['dst']:<26} {r['path']:<9} "
                f"{r['bytes'] / 1e6:>8.1f} "
                f"{'?' if gp is None else f'{gp:.4f}':>8} "
                f"{r.get('hop', 0):>4} {'ok' if r['ok'] else 'FAIL':<4} "
                f"{stages}"
                + (f"  trace={r['trace_id']}" if r.get("trace_id") else "")
                + (f"  err={r['error']}" if r.get("error") else "")
            )
        return
    if sub == "top":
        summary = state.summarize_transfers(
            group_by=args.group_by, limit=args.limit
        )
        if args.json:
            print(json.dumps(summary, indent=2, default=str))
            return
        print(
            f"== transfers: {summary['inflight']} in flight, "
            f"{summary['retries']} retries, {summary['stalled']} stalls, "
            f"{summary['leaked_buffers']} leaked buffers "
            f"({summary['leaked_bytes'] / 1e6:.1f} MB), "
            f"{summary['slow_link_events']} slow-link events =="
        )
        stages = summary.get("stage_seconds") or {}
        if stages:
            print(
                "stage seconds: "
                + "  ".join(f"{k}={v:g}s" for k, v in stages.items())
            )
        print(f"{'MB':>10} {'GiB/s':>8}  {args.group_by.upper()}")
        for g in summary["rows"]:
            gp = g.get("gib_per_s")
            paths = g.get("paths")
            path_s = (
                " ("
                + ",".join(
                    f"{p}:{n / 1e6:.1f}MB" for p, n in sorted(paths.items())
                )
                + ")"
                if paths
                else ""
            )
            print(
                f"{g['bytes'] / 1e6:>10.1f} "
                f"{'?' if gp is None else f'{gp:.4f}':>8}  "
                f"{g['group']}{path_s}"
                + ("  [SLOW]" if g.get("slow") else "")
            )
        if not summary["rows"]:
            print("  (no transfers recorded)")
        return
    raise SystemExit(f"unknown net subcommand {sub!r}")


def cmd_profile(args):
    """Continuous-profiling plane: record (boost the samplers) and export
    collapsed-stack / speedscope flame graphs with per-task attribution."""
    import ray_tpu

    _init(args)
    if args.profile_cmd == "record":
        n = ray_tpu.request_profile(hz=args.hz, duration_s=args.duration)
        print(
            f"profiling {n} workers (+driver) at {args.hz:g}Hz for "
            f"{args.duration:g}s"
        )
        time.sleep(args.duration + 0.5)
        print("done — export with: ray_tpu profile dump -o profile.json")
        return
    if args.profile_cmd == "dump":
        out = args.output or (
            "profile.txt" if args.format == "collapsed" else "profile.json"
        )
        n = ray_tpu.profile_dump(
            out, format=args.format, task_id=args.task_id,
            trace_id=args.trace_id,
        )
        print(f"wrote {args.format} flame graph ({n} profiles/lines) to {out}")
        if args.format == "speedscope":
            print("open it at https://www.speedscope.app/")
        return
    if args.profile_cmd == "top":
        from ray_tpu._private import sampler as _sampler
        from ray_tpu._private.worker import get_runtime

        _sampler.get_sampler().drain()
        rt = get_runtime()
        rows = rt.scheduler_rpc("profile_samples", (args.task_id, args.trace_id))
        print(_sampler.format_sample_summary(rows))


def _parse_quota(spec):
    """``CPU=4,memory=2e9,object_store_bytes=1e9`` → {resource: cap}."""
    if not spec:
        return None
    quota = {}
    for part in spec.split(","):
        if not part.strip():
            continue
        key, _, value = part.partition("=")
        if not value:
            raise SystemExit(f"bad --quota entry {part!r} (want resource=cap)")
        quota[key.strip()] = float(value)
    return quota


def cmd_job(args):
    from ray_tpu.job_submission import JobSubmissionClient

    _init(args)
    client = JobSubmissionClient()
    if args.job_cmd == "submit":
        job_id = client.submit_job(
            entrypoint=" ".join(args.entrypoint),
            priority=args.priority,
            weight=args.weight,
            quota=_parse_quota(args.quota),
        )
        print(f"submitted: {job_id}")
        if args.wait:
            status = client.wait_until_finished(job_id)
            print(f"status: {status.value}")
            print(client.get_job_logs(job_id))
    elif args.job_cmd == "status":
        print(client.get_job_status(args.job_id).value)
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.job_id))
    elif args.job_cmd == "stop":
        client.stop_job(args.job_id)
        print("stopped")
    elif args.job_cmd == "list":
        for rec in client.list_jobs():
            extra = ""
            if rec.get("admission"):
                extra = f"  [{rec['admission']}"
                if rec.get("queue_position"):
                    extra += f" #{rec['queue_position']}"
                extra += f" prio={rec.get('priority', 0)}]"
            print(
                f"{rec['job_id']}  {rec.get('status')}  "
                f"{rec['entrypoint'][:60]}{extra}"
            )
    elif args.job_cmd == "top":
        # `top`-style live usage across EVERY job the scheduler has seen
        # (driver included), heaviest first
        from ray_tpu.util import state

        rows = state.list_jobs()
        rows.sort(
            key=lambda r: -(
                sum((r.get("usage") or {}).values())
                + r.get("object_store_bytes", 0) / 2**30
            )
        )
        if args.json:
            print(json.dumps(rows, indent=2, default=str))
            return
        print(
            f"{'JOB':<18} {'PRIO':>4} {'WT':>5} {'ADMISSION':<10} "
            f"{'RUN':>5} {'READY':>7} {'PREEMPT':>7} {'OOM':>4} "
            f"{'OBJ_MB':>9}  USAGE / QUOTA"
        )
        for r in rows:
            usage = r.get("usage") or {}
            quota = r.get("quota") or {}
            pairs = sorted(set(usage) | set(quota))
            usage_s = " ".join(
                f"{k}:{usage.get(k, 0):g}"
                + (f"/{quota[k]:g}" if k in quota else "")
                for k in pairs
            )
            pos = f" #{r['queue_position']}" if r.get("queue_position") else ""
            print(
                f"{r['name']:<18} {r['priority']:>4} {r['weight']:>5g} "
                f"{r['admission'] + pos:<10} {r['running']:>5} "
                f"{r['ready']:>7} {r['preemptions']:>7} {r['oom_kills']:>4} "
                f"{r.get('object_store_bytes', 0) / 1e6:>9.1f}  {usage_s}"
            )
        if not rows:
            print("no jobs registered")


def cmd_serve(args):
    """``serve run/build/status/shutdown`` (parity: the serve CLI,
    ``python/ray/serve/scripts.py``)."""
    from ray_tpu import serve

    _init(args)
    if args.serve_cmd == "run":
        target = args.target
        if target.endswith((".yaml", ".yml")):
            if args.name != "default" or args.route_prefix:
                print("warning: --name/--route-prefix come from the yaml for "
                      "config deploys; flags ignored")
            handles = serve.deploy_config_file(target)
            print(f"deployed: {', '.join(handles)}")
        else:
            from ray_tpu.serve.schema import _import_bound_app

            serve.run(_import_bound_app(target), name=args.name,
                      route_prefix=args.route_prefix)
            print(f"deployed: {args.name}")
        if args.blocking:
            try:
                while True:
                    time.sleep(1)
            except KeyboardInterrupt:
                pass
    elif args.serve_cmd == "build":
        from ray_tpu.serve.schema import _import_bound_app

        config = serve.build(
            _import_bound_app(args.target),
            name=args.name,
            import_path=args.target,
            route_prefix=args.route_prefix,
        )
        text = serve.dump_config(config, args.output)
        if not args.output:
            print(text, end="")
        else:
            print(f"wrote {args.output}")
    elif args.serve_cmd == "status":
        try:
            st = serve.status()
        except ValueError:
            st = {}  # no controller -> nothing deployed
        print(json.dumps(st, indent=2))
        if not getattr(args, "json", False):
            # one-line health digest per deployment for quick triage
            for app, deps in st.items():
                for dep, row in deps.items():
                    health = row.get("health", "?")
                    drain = row.get("draining", 0)
                    extra = f" draining={drain}" if drain else ""
                    print(
                        f"{app}/{dep}: {health} "
                        f"{row.get('num_replicas', '?')}/"
                        f"{row.get('target', '?')} replicas{extra}"
                    )
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")


def cmd_dashboard(args):
    from ray_tpu.dashboard import start_dashboard

    _init(args)
    port = start_dashboard(port=args.port)
    print(f"dashboard at http://127.0.0.1:{port}/  (Ctrl-C to stop)")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        pass


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head or worker node")
    p.add_argument("--num-cpus", type=int, dest="num_cpus")
    p.add_argument("--num-tpus", type=int, dest="num_tpus")
    p.add_argument("--block", action="store_true")
    p.add_argument("--head", action="store_true", help="open the cluster socket")
    p.add_argument("--address", help="join an existing head as a worker node")
    p.add_argument(
        "--node-host",
        default="127.0.0.1",
        help="this node's address as reachable by peers (object server bind)",
    )
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("status", help="cluster resources and nodes")
    p.add_argument(
        "--backlog",
        action="store_true",
        help="also print the scheduler's per-resource-shape backlog "
        "(queued / leased / node-queued counts)",
    )
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("summary", help="task state summary")
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser(
        "memory",
        help="live objects by creation callsite with owner/bytes/leak "
        "classification (memory plane)",
    )
    p.add_argument(
        "--group-by",
        dest="group_by",
        choices=["callsite", "job", "node", "object"],
        default="callsite",
        help="server-side grouping (object = ungrouped per-object rows)",
    )
    p.add_argument(
        "--units",
        choices=sorted(_MEM_UNITS),
        default="MB",
        help="byte display units",
    )
    p.add_argument(
        "--leaks-only",
        dest="leaks_only",
        action="store_true",
        help="only rows flagged by the leak watchdog",
    )
    p.add_argument("--limit", type=int, default=50)
    p.add_argument("--json", action="store_true", help="raw JSON output")
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("timeline", help="dump chrome-trace task timeline")
    p.add_argument("--output", "-o")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser(
        "events", help="cluster event log (failure forensics)"
    )
    p.add_argument("--severity", help="filter: INFO | WARNING | ERROR")
    p.add_argument("--type", help="filter: WORKER_DIED, TASK_FAILED, ...")
    p.add_argument(
        "--job-id",
        dest="job_id",
        help="keep only events attributed to this job (job hex, "
        "explicit or embedded in the event's task/actor id)",
    )
    p.add_argument(
        "--since",
        help="only events after this point: a duration back from now "
        "(10m, 2h, 90s) or an absolute unix timestamp",
    )
    p.add_argument(
        "--follow",
        "-f",
        action="store_true",
        help="tail mode: keep polling for new events via the server-side "
        "after_event_id cursor (ctrl-c to stop)",
    )
    p.add_argument("--limit", type=int, default=200)
    p.add_argument("--json", action="store_true", help="raw JSON output")
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser(
        "doctor",
        help="one-shot cluster health digest: open incidents, SLO "
        "burn-rate status, top anomalies",
    )
    p.add_argument("--json", action="store_true", help="raw JSON output")
    p.set_defaults(fn=cmd_doctor)

    p = sub.add_parser(
        "incidents",
        help="alerting-plane incident records (open/merge/close with "
        "cross-plane root-cause digests)",
    )
    p.add_argument(
        "incident_id",
        nargs="*",
        help="show one incident's digest (`incidents <id>` or "
        "`incidents show <id>`)",
    )
    p.add_argument("--state", choices=["open", "closed"])
    p.add_argument("--type", help="filter: SLOW_LINK, SLO_BREACH, ...")
    p.add_argument("--limit", type=int, default=50)
    p.add_argument("--json", action="store_true", help="raw JSON output")
    p.set_defaults(fn=cmd_incidents)

    p = sub.add_parser(
        "train",
        help="training step-time & goodput attribution (step plane): "
        "runs | steps | stalls",
    )
    p.add_argument(
        "train_cmd",
        choices=["runs", "steps", "stalls"],
        help="runs = digest per run; steps = per-rank step waterfall; "
        "stalls = ingest stalls by operator + downtime ledger",
    )
    p.add_argument("--run", help="run name (RunConfig.name)")
    p.add_argument(
        "--rank", type=int, help="restrict the steps view to one rank"
    )
    p.add_argument("--limit", type=int, default=20, help="steps shown")
    p.add_argument("--json", action="store_true", help="raw JSON output")
    p.set_defaults(fn=cmd_train)

    p = sub.add_parser(
        "trace",
        help="reconstruct a request's span tree + critical-path latency "
        "decomposition (request-tracing plane)",
    )
    p.add_argument(
        "trace_id", nargs="?",
        help="trace id (from `trace --list`, a latency exemplar, the "
        "x-raytpu-trace-id serve header, or tracing.current_trace_id())",
    )
    p.add_argument("--list", action="store_true", help="list recent traces")
    p.add_argument("--limit", type=int, default=50)
    p.add_argument("--json", action="store_true")
    p.add_argument(
        "--flame", metavar="PATH",
        help="also export this trace's CPU samples as a flame graph "
        "(.txt = collapsed stacks, else speedscope JSON)",
    )
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser(
        "actors",
        help="actor fleet + launch lifecycle (control-plane "
        "observability): list | launch-profile",
    )
    p.add_argument(
        "actors_cmd",
        nargs="?",
        choices=["list", "launch-profile"],
        default="list",
        help="list = one row per actor with launch stage; launch-profile "
        "= per-stage launch-latency decomposition",
    )
    p.add_argument(
        "--pending",
        action="store_true",
        help="only creations still in flight, with the stage each is "
        "blocked in and for how long",
    )
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--json", action="store_true", help="raw JSON output")
    p.set_defaults(fn=cmd_actors)

    p = sub.add_parser(
        "decisions",
        help="scheduler/autoscaler decision flight recorder (why did "
        "the fleet scale / where did the actor land)",
    )
    p.add_argument(
        "--kind",
        choices=["placement", "autoscaler"],
        help="only one decision kind",
    )
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--json", action="store_true", help="raw JSON output")
    p.set_defaults(fn=cmd_decisions)

    p = sub.add_parser(
        "net",
        help="transfer plane (where did the wire go): "
        "links | transfers | top",
    )
    p.add_argument(
        "net_cmd",
        choices=["links", "transfers", "top"],
        help="links = per-(src,dst,path) ledger; transfers = recent stage "
        "decompositions; top = heaviest groups",
    )
    p.add_argument(
        "--group-by",
        dest="group_by",
        choices=["link", "path", "job", "task"],
        default="link",
        help="grouping for `top` (task = producing task name, e.g. the "
        "data executor's data:<stage> operators)",
    )
    p.add_argument("--limit", type=int, default=50)
    p.add_argument("--json", action="store_true", help="raw JSON output")
    p.set_defaults(fn=cmd_net)

    p = sub.add_parser(
        "profile",
        help="continuous sampling profiler: record / dump flame graphs",
    )
    psub = p.add_subparsers(dest="profile_cmd", required=True)
    ps = psub.add_parser("record", help="boost cluster-wide sampling")
    ps.add_argument("--hz", type=float, default=99.0)
    ps.add_argument("--duration", type=float, default=10.0)
    ps = psub.add_parser("dump", help="export aggregated samples")
    ps.add_argument("-o", "--output")
    ps.add_argument(
        "--format", choices=["speedscope", "collapsed"], default="speedscope"
    )
    ps.add_argument("--task-id", dest="task_id")
    ps.add_argument("--trace-id", dest="trace_id")
    ps = psub.add_parser("top", help="top sampled frames digest")
    ps.add_argument("--task-id", dest="task_id", default=None)
    ps.add_argument("--trace-id", dest="trace_id", default=None)
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("ckpt", help="checkpoint plane (list/verify/gc)")
    csub = p.add_subparsers(dest="ckpt_cmd", required=True)
    ps = csub.add_parser("list", help="list checkpoints (registry or --storage)")
    ps.add_argument("--storage", help="base path or URI (skips the cluster registry)")
    ps.add_argument("--limit", type=int, default=200)
    ps.add_argument("--json", action="store_true")
    ps = csub.add_parser("latest", help="newest COMMITTED checkpoint")
    ps.add_argument("--storage", help="base path or URI (skips the cluster registry)")
    ps = csub.add_parser("verify", help="re-verify a committed checkpoint's digests")
    ps.add_argument("prefix", help="checkpoint prefix (path or URI)")
    ps = csub.add_parser("gc", help="retention GC over a base path or URI")
    ps.add_argument("--storage", required=True)
    ps.add_argument("--keep", type=int, help="keep the newest N committed")
    ps.add_argument("--max-age-s", type=float, dest="max_age_s")
    ps.add_argument(
        "--clear-cache", action="store_true",
        help="also drop the local Checkpoint.from_uri restore cache",
    )
    p.set_defaults(fn=cmd_ckpt)

    p = sub.add_parser("job", help="job submission")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    ps = jsub.add_parser("submit")
    ps.add_argument("--wait", action="store_true")
    ps.add_argument(
        "--priority",
        type=int,
        default=0,
        help="job priority: ranks admission order and preemption "
        "(higher preempts lower)",
    )
    ps.add_argument(
        "--weight",
        type=float,
        default=1.0,
        help="weighted-fair-queueing share (dispatch quantum multiplier)",
    )
    ps.add_argument(
        "--quota",
        help="per-resource live-usage caps, e.g. "
        "CPU=4,memory=2e9,object_store_bytes=1e9",
    )
    ps.add_argument("entrypoint", nargs=argparse.REMAINDER)
    jsub.add_parser("status").add_argument("job_id")
    jsub.add_parser("logs").add_argument("job_id")
    jsub.add_parser("stop").add_argument("job_id")
    jsub.add_parser("list")
    ps = jsub.add_parser(
        "top", help="live per-job usage vs quota, heaviest first"
    )
    ps.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser("serve", help="model serving")
    ssub = p.add_subparsers(dest="serve_cmd", required=True)
    ps = ssub.add_parser("run", help="deploy a config yaml or module:app")
    ps.add_argument("target")
    ps.add_argument("--name", default="default")
    ps.add_argument("--route-prefix", dest="route_prefix")
    ps.add_argument("--blocking", action="store_true")
    ps = ssub.add_parser("build", help="emit declarative config for module:app")
    ps.add_argument("target")
    ps.add_argument("--name", default="default")
    ps.add_argument("--route-prefix", dest="route_prefix")
    ps.add_argument("--output", "-o")
    ps = ssub.add_parser("status")
    ps.add_argument("--json", action="store_true")
    ssub.add_parser("shutdown")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("dashboard", help="start the HTTP dashboard")
    p.add_argument("--port", type=int, default=8765)
    p.set_defaults(fn=cmd_dashboard)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
