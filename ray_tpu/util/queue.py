"""Distributed FIFO queue backed by an actor.

Parity: ``python/ray/util/queue.py`` — Queue with put/get/qsize, usable from
any task/actor.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote(num_cpus=0)
class _QueueActor:
    def __init__(self, maxsize: int):
        import collections

        self.maxsize = maxsize
        self.q = collections.deque()

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self.q) >= self.maxsize:
            return False
        self.q.append(item)
        return True

    def get_nowait(self):
        if not self.q:
            return (False, None)
        return (True, self.q.popleft())

    def qsize(self) -> int:
        return len(self.q)

    def empty(self) -> bool:
        return not self.q

    def get_batch(self, n: int) -> List:
        out = []
        while self.q and len(out) < n:
            out.append(self.q.popleft())
        return out


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        self._actor = _QueueActor.options(**(actor_options or {})).remote(maxsize)

    def put(self, item, block: bool = True, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok = ray_tpu.get(self._actor.put.remote(item), timeout=60)
            if ok:
                return
            if not block:
                raise Full
            if deadline is not None and time.monotonic() >= deadline:
                raise Full
            time.sleep(0.01)

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray_tpu.get(self._actor.get_nowait.remote(), timeout=60)
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty
            time.sleep(0.01)

    def put_nowait(self, item):
        self.put(item, block=False)

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote(), timeout=60)

    def empty(self) -> bool:
        return ray_tpu.get(self._actor.empty.remote(), timeout=60)

    def get_batch(self, n: int) -> List:
        return ray_tpu.get(self._actor.get_batch.remote(n), timeout=60)

    def shutdown(self):
        ray_tpu.kill(self._actor)
