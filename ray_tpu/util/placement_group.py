"""Placement groups: gang scheduling of resource bundles.

Parity: ``python/ray/util/placement_group.py:145`` +
``gcs_placement_group_manager.h:230`` (2PC bundle reservation) — strategies
PACK / SPREAD / STRICT_PACK / STRICT_SPREAD. The TPU extension: a bundle list
may be generated from a slice topology so one PG == one ICI-connected slice
(see ``ray_tpu.util.tpu_pod``).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu import exceptions as exc
from ray_tpu._private.ids import PlacementGroupID, pg_ready_sentinel
from ray_tpu._private.scheduler import PlacementGroupState
from ray_tpu._private.worker import ObjectRef, get_runtime


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]]):
        self.id = pg_id
        self.bundle_specs = bundles

    def ready(self) -> ObjectRef:
        """An ObjectRef resolving when the PG is placed (parity: ``pg.ready()``).

        The scheduler commits a sentinel object the moment the 2PC placement
        commits, so this is push-notified, not probe-polled."""
        return ObjectRef(pg_ready_sentinel(self.id))

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        rt = get_runtime()
        ready, _ = rt.wait([pg_ready_sentinel(self.id)], 1, timeout_seconds)
        return bool(ready)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"invalid strategy {strategy}")
    if not bundles:
        raise ValueError("bundles must be non-empty")
    for b in bundles:
        if not b or any(v < 0 for v in b.values()):
            raise ValueError(f"invalid bundle {b}")
    rt = get_runtime()
    pg_id = PlacementGroupID.from_random()
    state = PlacementGroupState(
        pg_id=pg_id,
        bundles=[{k: float(v) for k, v in b.items()} for b in bundles],
        strategy=strategy,
        name=name,
    )
    if hasattr(rt, "scheduler"):
        rt.scheduler.post(("create_pg", state))
    else:
        rt._send(("cmd", ("create_pg", state)))
    return PlacementGroup(pg_id, state.bundles)


def remove_placement_group(pg: PlacementGroup) -> None:
    rt = get_runtime()
    if hasattr(rt, "scheduler"):
        rt.scheduler.post(("remove_pg", pg.id))
    else:
        rt._send(("cmd", ("remove_pg", pg.id)))


def placement_group_table() -> dict:
    rt = get_runtime()
    if not hasattr(rt, "scheduler"):
        raise RuntimeError("driver only")
    out = {}
    for pg_id, st in rt.scheduler.placement_groups.items():
        out[pg_id.hex()] = {
            "state": st.state,
            "strategy": st.strategy,
            "bundles": st.bundles,
            "name": st.name,
        }
    return out
