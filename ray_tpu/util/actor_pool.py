"""ActorPool: map work over a fixed set of actors.

Parity: ``python/ray/util/actor_pool.py`` (API surface only; the
bookkeeping here is sequence-number based rather than index/future maps).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    """Round-robins ``fn(actor, value)`` calls over a fixed actor fleet.

    Internally each submission gets a monotonically increasing sequence
    number; ``get_next`` emits results in sequence order while
    ``get_next_unordered`` emits whichever future lands first.
    """

    def __init__(self, actors: List[Any]):
        self._available = deque(actors)
        # seq -> future, and future -> (seq, actor) for the reverse hop.
        self._by_seq: dict = {}
        self._inflight: dict = {}
        self._submit_seq = 0
        self._emit_seq = 0
        self._backlog: deque = deque()

    def submit(self, fn: Callable, value: Any) -> None:
        if not self._available:
            self._backlog.append((fn, value))
            return
        actor = self._available.pop()
        future = fn(actor, value)
        seq = self._submit_seq
        self._submit_seq += 1
        self._by_seq[seq] = future
        self._inflight[future] = (seq, actor)

    def has_next(self) -> bool:
        return bool(self._by_seq) or bool(self._backlog)

    def get_next(self, timeout=None) -> Any:
        future = self._by_seq.pop(self._emit_seq, None)
        if future is None:
            raise StopIteration("no pending results")
        self._emit_seq += 1
        value = ray_tpu.get(future, timeout=timeout)
        self._recycle(future)
        return value

    def get_next_unordered(self, timeout=None) -> Any:
        if not self._inflight:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        future = ready[0]
        seq, _actor = self._inflight[future]
        self._by_seq.pop(seq, None)
        value = ray_tpu.get(future)
        self._recycle(future)
        return value

    def _recycle(self, future):
        _seq, actor = self._inflight.pop(future)
        self._available.append(actor)
        if self._backlog:
            fn, value = self._backlog.popleft()
            self.submit(fn, value)

    def map(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self._inflight or self._backlog:
            yield self.get_next_unordered()

    def has_free(self) -> bool:
        return bool(self._available)

    def pop_idle(self):
        return self._available.pop() if self._available else None

    def push(self, actor):
        self._available.append(actor)
