"""User-facing scheduling strategies.

Parity: ``python/ray/util/scheduling_strategies.py`` — PlacementGroup /
NodeAffinity / Spread strategies passed via ``.options(scheduling_strategy=)``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ray_tpu._private.task_spec import SchedulingStrategy as _Internal


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: "PlacementGroup"  # noqa: F821
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False

    def to_internal(self) -> _Internal:
        return _Internal(
            kind="PLACEMENT_GROUP",
            placement_group_id=self.placement_group.id,
            bundle_index=self.placement_group_bundle_index,
        )


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False

    def to_internal(self) -> _Internal:
        return _Internal(kind="NODE_AFFINITY", node_id=self.node_id, soft=self.soft)


@dataclass
class SpreadSchedulingStrategy:
    def to_internal(self) -> _Internal:
        return _Internal(kind="SPREAD")


SPREAD = "SPREAD"
DEFAULT = "DEFAULT"
