"""Fault-injection utilities for chaos testing.

Parity: ``python/ray/_private/test_utils.py:1500`` — ``ResourceKillerActor``
(raylet SIGKILL at ``:1549``) and ``WorkerKillerActor`` (``:1597``): actors
that repeatedly kill cluster components while workloads run, proving the
retry/restart machinery under concurrent load rather than one-shot tests.
"""

from __future__ import annotations

import os
import random
import signal
import time

import ray_tpu


@ray_tpu.remote(num_cpus=0)
class WorkerKillerActor:
    """Periodically SIGKILLs a random busy task worker."""

    def __init__(self, kill_interval_s: float = 0.5, seed: int = 0):
        self.interval = kill_interval_s
        self.rng = random.Random(seed)
        self.killed = 0
        self._stop = False

    def run(self, duration_s: float = 10.0) -> int:
        from ray_tpu.util import state as state_api

        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline and not self._stop:
            time.sleep(self.interval)
            try:
                workers = [
                    w
                    for w in state_api.list_workers()
                    if w["state"] == "busy" and w.get("pid") and w["pid"] != os.getpid()
                ]
            except Exception:
                continue
            if not workers:
                continue
            victim = self.rng.choice(workers)
            try:
                os.kill(victim["pid"], signal.SIGKILL)
                self.killed += 1
            except (ProcessLookupError, PermissionError):
                pass
        return self.killed

    def stop(self):
        self._stop = True
        return self.killed


@ray_tpu.remote(num_cpus=0)
class NodeKillerActor:
    """SIGKILLs node-daemon processes by pid (cluster fixture provides pids).

    Parity: ``NodeKillerBase`` / raylet SIGKILL (test_utils.py:1549)."""

    def kill_pid(self, pid: int) -> bool:
        try:
            os.kill(pid, signal.SIGKILL)
            return True
        except (ProcessLookupError, PermissionError):
            return False
