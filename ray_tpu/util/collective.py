"""Host-level collective communication.

Parity: ``ray.util.collective`` (``collective.py:120-531``) — group
management + allreduce/allgather/reducescatter/broadcast/barrier for host
(numpy) tensors, rendezvous through a named actor (the reference stores the
NCCL unique id in a named ``Rendezvous`` actor, ``nccl_collective_group.py:29``).

Device tensors deliberately take the other plane: on TPU, collectives between
chips belong *inside* compiled XLA programs over ICI (``jax.lax.psum`` et al,
SURVEY.md §5 "Distributed communication backend") — this module is the
DCN/host path for CPU data and control coordination.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu

_GROUP_PREFIX = "COLLECTIVE_GROUP:"


@ray_tpu.remote(num_cpus=0, max_concurrency=64)
class _GroupActor:
    def __init__(self, world_size: int):
        import threading

        self.world_size = world_size
        self._lock = threading.Lock()
        # (round, op) -> {rank: array}
        self.contribs: Dict[tuple, Dict[int, Any]] = {}
        self.results: Dict[tuple, Any] = {}
        self._events: Dict[tuple, Any] = {}

    def _event(self, key):
        with self._lock:
            return self._event_locked(key)

    def _event_locked(self, key):
        import threading

        ev = self._events.get(key)
        if ev is None:
            ev = self._events[key] = threading.Event()
        return ev

    def contribute_and_wait(self, key: tuple, rank: int, value, timeout: float):
        """Deposit a contribution and block until the collective completes
        (event-notified; replaces the round-1 fetch-poll loop)."""
        with self._lock:
            entry = self.contribs.setdefault(key, {})
            entry[rank] = value
            done = len(entry) == self.world_size
            if done:
                self.results[key] = self._finish(key, entry)
                del self.contribs[key]
        ev = self._event(key)
        if done:
            ev.set()
        elif not ev.wait(timeout):
            raise TimeoutError(f"collective {key} timed out")
        return self.results[key]

    def contribute(self, key: tuple, rank: int, value):
        with self._lock:
            entry = self.contribs.setdefault(key, {})
            entry[rank] = value
            if len(entry) == self.world_size:
                self.results[key] = self._finish(key, entry)
                del self.contribs[key]
                # _event_locked: plain _event() re-takes the non-reentrant
                # lock and would deadlock here
                self._event_locked(key).set()
        return True

    def _finish(self, key, entry):
        op = key[1]
        parts = [entry[r] for r in range(self.world_size)]
        if op == "allreduce_sum":
            return sum(parts[1:], parts[0])
        if op == "allreduce_max":
            out = parts[0]
            for p in parts[1:]:
                out = np.maximum(out, p)
            return out
        if op == "allgather":
            return parts
        if op == "reducescatter":
            total = sum(parts[1:], parts[0])
            return np.array_split(total, self.world_size)
        if op == "broadcast":
            return next(p for p in parts if p is not None)
        if op == "barrier":
            return True
        raise ValueError(op)

    def p2p_send(self, key: tuple, value) -> bool:
        """Deposit a point-to-point payload for one receiver. Payloads queue
        per key, so two sends on the same (src, dst, tag) before the matching
        recv both arrive in order (the reference's send/recv never loses a
        message)."""
        with self._lock:
            self.results.setdefault(key, []).append(value)
            # set inside the critical section: a delayed set() after the
            # final recv drained the key would otherwise leave a set event
            # with no queued payload (KeyError on the next recv)
            self._event_locked(key).set()
        return True

    def p2p_recv(self, key: tuple, timeout: float):
        ev = self._event(key)
        if not ev.wait(timeout):
            raise TimeoutError(f"recv {key} timed out")
        with self._lock:
            queue = self.results[key]
            value = queue.pop(0)
            if not queue:
                del self.results[key]
                # allow tag reuse: the next send on this key re-sets the event
                self._events.pop(key, None)
        return value

    def fetch(self, key: tuple):
        return self.results.get(key)

    def gc(self, before_round: int):
        with self._lock:
            for k in [k for k in self.results if k[0] < before_round]:
                del self.results[k]
                self._events.pop(k, None)
        return True


class CollectiveGroup:
    def __init__(self, group_name: str, world_size: int, rank: int):
        self.group_name = group_name
        self.world_size = world_size
        self.rank = rank
        self._round = 0
        name = _GROUP_PREFIX + group_name
        try:
            self._actor = ray_tpu.get_actor(name)
        except ValueError:
            try:
                # every rank blocks one actor thread in contribute_and_wait:
                # size the thread pool to the world so no world size deadlocks
                self._actor = _GroupActor.options(
                    name=name, max_concurrency=max(64, 2 * world_size + 4)
                ).remote(world_size)
            except ValueError:
                self._actor = ray_tpu.get_actor(name)

    def _run(self, op: str, value, timeout: float = 300.0):
        self._round += 1
        key = (self._round, op)
        result = ray_tpu.get(
            self._actor.contribute_and_wait.remote(key, self.rank, value, timeout),
            timeout=timeout + 10,
        )
        if self._round % 100 == 0:
            self._actor.gc.remote(self._round - 10)
        return result

    def allreduce(self, tensor: np.ndarray, op: str = "sum") -> np.ndarray:
        return self._run(f"allreduce_{op}", np.asarray(tensor))

    def allgather(self, tensor: np.ndarray) -> List[np.ndarray]:
        return self._run("allgather", np.asarray(tensor))

    def reducescatter(self, tensor: np.ndarray) -> np.ndarray:
        return self._run("reducescatter", np.asarray(tensor))[self.rank]

    def broadcast(self, tensor: Optional[np.ndarray], src_rank: int = 0) -> np.ndarray:
        value = np.asarray(tensor) if self.rank == src_rank else None
        return self._run("broadcast", value)

    def barrier(self) -> None:
        self._run("barrier", True)

    # -- point-to-point (parity: ray.util.collective send/recv,
    # collective.py:531) ---------------------------------------------------

    def send(self, tensor: np.ndarray, dst_rank: int, tag: int = 0) -> None:
        key = ("p2p", self.rank, dst_rank, tag)
        ray_tpu.get(
            self._actor.p2p_send.remote(key, np.asarray(tensor)), timeout=300
        )

    def recv(self, src_rank: int, tag: int = 0, timeout: float = 300.0) -> np.ndarray:
        key = ("p2p", src_rank, self.rank, tag)
        return ray_tpu.get(
            self._actor.p2p_recv.remote(key, timeout), timeout=timeout + 10
        )


def init_collective_group(world_size: int, rank: int, group_name: str = "default") -> CollectiveGroup:
    """Parity: ``ray.util.collective.init_collective_group``."""
    return CollectiveGroup(group_name, world_size, rank)


def destroy_collective_group(group: CollectiveGroup) -> None:
    try:
        ray_tpu.kill(group._actor)
    except Exception:
        pass
