"""Application metrics: Counter / Gauge / Histogram.

Parity: ``python/ray/util/metrics.py`` + the metrics agent's Prometheus
exposition (``python/ray/_private/metrics_agent.py:483``). Metrics recorded in
any process are aggregated in the GCS KV (namespace ``metrics``) and exposed
in Prometheus text format via :func:`prometheus_text`.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.worker import get_runtime

_NS = "metrics"
_lock = threading.Lock()
# local shadow (flushed to GCS KV on record): name -> {labels_json: value}
_local: Dict[str, Dict[str, object]] = {}


def _flush(name: str, kind: str, description: str, data: Dict[str, object]):
    try:
        rt = get_runtime()
        blob = json.dumps({"kind": kind, "description": description, "data": data}).encode()
        if hasattr(rt, "scheduler_rpc"):
            rt.scheduler_rpc("kv_put", (_NS, name.encode(), blob, True))
        else:
            rt.rpc("kv_put", _NS, name.encode(), blob, True)
    except Exception:
        pass  # metrics never break the app


class _Metric:
    KIND = "untyped"

    def __init__(self, name: str, description: str = "", tag_keys: Tuple[str, ...] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        with _lock:
            _local.setdefault(name, {})

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> str:
        merged = {**self._default_tags, **(tags or {})}
        return json.dumps(merged, sort_keys=True)

    def _store(self, key: str, value):
        with _lock:
            _local[self._name][key] = value
            snapshot = dict(_local[self._name])
        _flush(self._name, self.KIND, self._description, snapshot)


class Counter(_Metric):
    KIND = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with _lock:
            current = _local[self._name].get(key, 0.0)
        self._store(key, current + value)


class Gauge(_Metric):
    KIND = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        self._store(self._key(tags), value)


class Histogram(_Metric):
    KIND = "histogram"

    def __init__(self, name, description="", boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self._boundaries = boundaries or [0.1, 1, 10, 100, 1000]

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with _lock:
            entry = _local[self._name].get(key) or {
                "count": 0,
                "sum": 0.0,
                "buckets": [0] * (len(self._boundaries) + 1),
            }
            entry = json.loads(json.dumps(entry))  # copy
        entry["count"] += 1
        entry["sum"] += value
        for i, b in enumerate(self._boundaries):
            if value <= b:
                entry["buckets"][i] += 1
                break
        else:
            entry["buckets"][-1] += 1
        entry["boundaries"] = self._boundaries
        self._store(key, entry)


def prometheus_text() -> str:
    """All recorded metrics in Prometheus exposition format (driver-side)."""
    rt = get_runtime()
    if hasattr(rt, "scheduler_rpc"):
        keys = rt.scheduler_rpc("kv_keys", (_NS, b""))
        get = lambda k: rt.scheduler_rpc("kv_get", (_NS, k))  # noqa: E731
    else:
        keys = rt.rpc("kv_keys", _NS, b"")
        get = lambda k: rt.rpc("kv_get", _NS, k)  # noqa: E731
    lines = []
    for key in keys:
        raw = get(key)
        if raw is None:
            continue
        payload = json.loads(raw)
        name = key.decode()
        kind = payload["kind"]
        lines.append(f"# HELP {name} {payload.get('description', '')}")
        lines.append(f"# TYPE {name} {kind if kind != 'untyped' else 'gauge'}")
        for labels_json, value in payload["data"].items():
            labels = json.loads(labels_json)
            label_str = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
            label_part = "{" + label_str + "}" if label_str else ""
            if kind == "histogram" and isinstance(value, dict):
                lines.append(f"{name}_count{label_part} {value['count']}")
                lines.append(f"{name}_sum{label_part} {value['sum']}")
            else:
                lines.append(f"{name}{label_part} {value}")
    return "\n".join(lines) + "\n"
