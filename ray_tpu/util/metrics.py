"""Application metrics: Counter / Gauge / Histogram.

Parity: ``python/ray/util/metrics.py`` + the metrics agent's Prometheus
exposition (``python/ray/_private/metrics_agent.py:483``). Records update a
process-local shadow and ride the telemetry plane
(``ray_tpu._private.telemetry``): the background flusher ships at most ONE
snapshot per metric per ``metrics_report_interval_ms`` — the seed did a
blocking KV RPC on *every* ``Counter.inc()`` and silently swallowed
failures. The scheduler merges per-process snapshots (counters/histograms
sum across processes, gauges last-writer-wins) into the GCS KV, and
:func:`prometheus_text` exposes them plus the runtime-internal series
(scheduler queue depth, handler event_stats, object-store usage, fastcopy
stage bandwidth, telemetry drop counters) in Prometheus text format.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.worker import get_runtime

_NS = "metrics"
_lock = threading.Lock()
# local shadow (shipped in batches by the telemetry flusher): name ->
# {labels_json: value}
_local: Dict[str, Dict[str, object]] = {}


def _enqueue(name: str, kind: str, description: str, data: Dict[str, object]):
    """Queue this metric's latest snapshot for the next batched flush (one
    KV write per interval per metric, not per record). Loss is accounted by
    ``ray_tpu_telemetry_dropped_total``, not swallowed."""
    from ray_tpu._private import telemetry

    telemetry.record_metric(name, kind, description, data)


class _Metric:
    KIND = "untyped"

    def __init__(self, name: str, description: str = "", tag_keys: Tuple[str, ...] = ()):
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        with _lock:
            _local.setdefault(name, {})

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> str:
        merged = {**self._default_tags, **(tags or {})}
        return json.dumps(merged, sort_keys=True)

    def _store(self, key: str, value):
        with _lock:
            _local[self._name][key] = value
            snapshot = dict(_local[self._name])
        _enqueue(self._name, self.KIND, self._description, snapshot)


class Counter(_Metric):
    KIND = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with _lock:
            current = _local[self._name].get(key, 0.0)
        self._store(key, current + value)


class Gauge(_Metric):
    KIND = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        self._store(self._key(tags), value)


# default histogram grid: sub-millisecond buckets resolve dispatch-path
# costs (direct-call send, lease grant, arg materialization live in the
# 10us-1ms band the old [0.1, 1, 10, 100, 1000] grid lumped into one
# bucket), still reaching 10s for slow requests. Units are whatever the
# metric observes — for *_ms series this spans 10us .. 10s.
DEFAULT_HISTOGRAM_BOUNDARIES: List[float] = [
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1, 2.5, 5, 10, 25, 50, 100, 250, 500,
    1000, 2500, 5000, 10000,
]

# per-metric boundary overrides (configure_histogram_boundaries), consulted
# at CONSTRUCTION time; env var RAY_TPU_HIST_BUCKETS_<NAME> (comma-separated
# floats, metric name uppercased with non-alnum -> _) wins over both
_boundary_overrides: Dict[str, List[float]] = {}


def configure_histogram_boundaries(name: str, boundaries: List[float]) -> None:
    """Set the bucket bounds for histograms named ``name`` created AFTER
    this call (per-metric bucket configurability). Bounds must ascend."""
    bounds = list(boundaries)
    if bounds != sorted(bounds) or not bounds:
        raise ValueError("histogram boundaries must be ascending and non-empty")
    with _lock:
        _boundary_overrides[name] = bounds


def _env_boundaries(name: str) -> Optional[List[float]]:
    import os
    import re

    key = "RAY_TPU_HIST_BUCKETS_" + re.sub(r"[^A-Za-z0-9]", "_", name).upper()
    raw = os.environ.get(key)
    if not raw:
        return None
    try:
        bounds = [float(p) for p in raw.split(",") if p.strip()]
        return bounds if bounds == sorted(bounds) and bounds else None
    except ValueError:
        return None


def resolve_boundaries(name: str, explicit: Optional[List[float]] = None) -> List[float]:
    """Boundary resolution order: env override > configure_histogram_
    boundaries > constructor argument > the default grid."""
    env = _env_boundaries(name)
    if env is not None:
        return env
    with _lock:
        override = _boundary_overrides.get(name)
    if override is not None:
        return list(override)
    if explicit:
        # preserved verbatim: int bounds render as le="1", not le="1.0"
        return list(explicit)
    return list(DEFAULT_HISTOGRAM_BOUNDARIES)


class Histogram(_Metric):
    KIND = "histogram"

    def __init__(self, name, description="", boundaries: Optional[List[float]] = None,
                 tag_keys: Tuple[str, ...] = ()):
        super().__init__(name, description, tag_keys)
        self._boundaries = resolve_boundaries(name, boundaries)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        self.observe_many((value,), tags)

    def observe_many(self, values, tags: Optional[Dict[str, str]] = None):
        """Fold a batch of observations in with ONE entry copy + snapshot
        enqueue (observe() per value pays a json round-trip each — hot
        per-step callers like the train step plane accumulate locally and
        flush batches through here)."""
        if not values:
            return
        key = self._key(tags)
        with _lock:
            entry = _local[self._name].get(key) or {
                "count": 0,
                "sum": 0.0,
                "buckets": [0] * (len(self._boundaries) + 1),
            }
            entry = json.loads(json.dumps(entry))  # copy
        for value in values:
            entry["count"] += 1
            entry["sum"] += value
            for i, b in enumerate(self._boundaries):
                if value <= b:
                    entry["buckets"][i] += 1
                    break
            else:
                entry["buckets"][-1] += 1
        entry["boundaries"] = self._boundaries
        self._store(key, entry)


def _sync_cluster_telemetry(rt) -> None:
    """Read-your-writes for the batched pipeline: flush this process's
    buffer, then ask the scheduler to pull every worker's (bounded wait).
    Remote (socket-attached) drivers skip the cluster pull — their view may
    lag one flush interval."""
    from ray_tpu._private import telemetry

    telemetry.flush()
    scheduler = getattr(rt, "scheduler", None)
    if scheduler is not None:
        try:
            scheduler.request_telemetry_flush()
        except Exception:
            pass


def _format_series(lines: List[str], name: str, kind: str, description: str,
                   data: Dict[str, object]) -> None:
    lines.append(f"# HELP {name} {description}")
    lines.append(f"# TYPE {name} {kind if kind != 'untyped' else 'gauge'}")
    for labels_json, value in data.items():
        labels = json.loads(labels_json) if labels_json.startswith("{") else {}
        label_str = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
        label_part = "{" + label_str + "}" if label_str else ""
        if kind == "histogram" and isinstance(value, dict):
            lines.append(f"{name}_count{label_part} {value['count']}")
            lines.append(f"{name}_sum{label_part} {value['sum']}")
            bounds = value.get("boundaries") or []
            cumulative = 0
            for b, n in zip(bounds, value.get("buckets", ())):
                cumulative += n
                le = "{" + ",".join(filter(None, [label_str, f'le="{b}"'])) + "}"
                lines.append(f"{name}_bucket{le} {cumulative}")
            le_inf = "{" + ",".join(filter(None, [label_str, 'le="+Inf"'])) + "}"
            lines.append(f"{name}_bucket{le_inf} {value['count']}")
        else:
            lines.append(f"{name}{label_part} {value}")


def prometheus_text() -> str:
    """All recorded metrics — application (GCS KV aggregated) plus the
    scheduler's runtime-internal series — in Prometheus exposition format."""
    rt = get_runtime()
    _sync_cluster_telemetry(rt)
    if hasattr(rt, "scheduler_rpc"):
        keys = rt.scheduler_rpc("kv_keys", (_NS, b""))
        get = lambda k: rt.scheduler_rpc("kv_get", (_NS, k))  # noqa: E731
        runtime_series = rt.scheduler_rpc("runtime_metrics", ())
    else:
        keys = rt.rpc("kv_keys", _NS, b"")
        get = lambda k: rt.rpc("kv_get", _NS, k)  # noqa: E731
        runtime_series = rt.rpc("runtime_metrics")
    lines: List[str] = []
    for key in sorted(keys):
        raw = get(key)
        if raw is None:
            continue
        payload = json.loads(raw)
        _format_series(
            lines,
            key.decode(),
            payload["kind"],
            payload.get("description", ""),
            payload["data"],
        )
    for series in runtime_series or ():
        _format_series(
            lines,
            series["name"],
            series.get("kind", "gauge"),
            series.get("description", ""),
            series.get("data", {}),
        )
    return "\n".join(lines) + "\n"
