"""Joblib backend: run scikit-learn / joblib workloads as cluster tasks.

Parity: ``ray.util.joblib`` (``python/ray/util/joblib/``) — registers a
joblib parallel backend so ``with parallel_backend("ray_tpu"): ...`` fans
``Parallel(n_jobs=...)`` batches out as framework tasks instead of local
processes.
"""

from __future__ import annotations

from typing import Any


def register_ray_tpu() -> None:
    """Register the backend (parity: ``ray.util.joblib.register_ray``)."""
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray_tpu", _RayTpuBackend)


def _base():
    from joblib._parallel_backends import ParallelBackendBase

    return ParallelBackendBase


class _RayTpuBackend(_base()):
    """Each dispatched joblib batch becomes one framework task."""

    supports_timeout = True

    def configure(self, n_jobs=1, parallel=None, **kwargs) -> int:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init(ignore_reinit_error=True)
        self.parallel = parallel
        return self.effective_n_jobs(n_jobs)

    def effective_n_jobs(self, n_jobs) -> int:
        import ray_tpu

        cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
        if n_jobs is None or n_jobs == -1:
            return max(1, cpus)
        return max(1, int(n_jobs))

    def apply_async(self, func, callback=None):
        import cloudpickle

        ref = _run_joblib_batch.remote(cloudpickle.dumps(func))
        return _AsyncResult(ref, callback)

    def abort_everything(self, ensure_ready=True):
        pass


class _AsyncResult:
    """Duck-types multiprocessing.pool.AsyncResult for joblib."""

    def __init__(self, ref, callback):
        self._ref = ref
        self._callback = callback
        self._value: Any = None
        self._done = False
        if callback is not None:
            import threading

            threading.Thread(target=self._wait_and_callback, daemon=True).start()

    def _wait_and_callback(self):
        value = self.get()
        self._callback(value)

    def get(self, timeout=None):
        import ray_tpu

        if not self._done:
            # timeout=None is joblib's "wait forever" — pass it through
            self._value = ray_tpu.get(self._ref, timeout=timeout)
            self._done = True
        return self._value


import ray_tpu as _ray_tpu  # noqa: E402  (module-level: registered once)


@_ray_tpu.remote
def _run_joblib_batch(blob):
    import cloudpickle as cp

    return cp.loads(blob)()
