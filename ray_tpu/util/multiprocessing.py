"""multiprocessing.Pool shim over cluster tasks.

Parity: ``ray.util.multiprocessing.Pool`` — drop-in Pool whose workers are
cluster tasks, so ``pool.map`` scales past one machine.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


@ray_tpu.remote
def _run_chunk(fn_blob: bytes, chunk: List[tuple], is_star: bool):
    import cloudpickle

    fn = cloudpickle.loads(fn_blob)
    if is_star:
        return [fn(*args) for args in chunk]
    return [fn(args) for args in chunk]


class Pool:
    def __init__(self, processes: Optional[int] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._processes = processes or int(ray_tpu.cluster_resources().get("CPU", 1))
        self._closed = False

    def _chunks(self, iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (4 * self._processes) or 1)
        for i in range(0, len(items), chunksize):
            yield items[i : i + chunksize]

    def _map(self, func, iterable, chunksize, is_star) -> List[Any]:
        import cloudpickle

        if self._closed:
            raise ValueError("Pool is closed")
        blob = cloudpickle.dumps(func)
        refs = [
            _run_chunk.remote(blob, chunk, is_star)
            for chunk in self._chunks(iterable, chunksize)
        ]
        return list(itertools.chain.from_iterable(ray_tpu.get(refs)))

    def map(self, func: Callable, iterable: Iterable, chunksize: Optional[int] = None):
        return self._map(func, iterable, chunksize, is_star=False)

    def starmap(self, func: Callable, iterable: Iterable, chunksize: Optional[int] = None):
        return self._map(func, iterable, chunksize, is_star=True)

    def apply(self, func: Callable, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args: tuple = (), kwds: Optional[dict] = None):
        import cloudpickle

        blob = cloudpickle.dumps(lambda: func(*args, **(kwds or {})))

        @ray_tpu.remote
        def _run(b):
            import cloudpickle as cp

            return cp.loads(b)()

        ref = _run.remote(blob)

        class _Result:
            def get(self, timeout: Optional[float] = None):
                return ray_tpu.get(ref, timeout=timeout)

            def ready(self):
                done, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
                return bool(done)

        return _Result()

    def imap(self, func: Callable, iterable: Iterable, chunksize: int = 1):
        import cloudpickle

        blob = cloudpickle.dumps(func)
        refs = [
            _run_chunk.remote(blob, chunk, False)
            for chunk in self._chunks(iterable, chunksize)
        ]
        for ref in refs:
            yield from ray_tpu.get(ref)

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
