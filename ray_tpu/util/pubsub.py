"""General publish/subscribe channels over the cluster RPC substrate.

Parity: the reference's GCS pubsub (``src/ray/pubsub/publisher.h:38``,
``subscriber.h``) — named channels any process can publish to, with
push-based delivery to every subscriber. The head fans a published message
out once per subscriber process; within a process, every local subscription
gets its own queue. Messages are delivered to CURRENT subscribers only (no
replay) — the reference's semantics.

    from ray_tpu.util.pubsub import publish, subscribe

    sub = subscribe("alerts")           # driver, task, or actor — anywhere
    publish("alerts", {"sev": "high"})  # any process
    msg = sub.get(timeout=5)            # -> {"sev": "high"}
    for msg in sub:                     # or iterate (blocking)
        ...
    sub.close()

Internals (``_private/scheduler.py`` ``_pubsub_fanout``): worker subscribers
receive ``("pubsub_msg", channel, blob)`` pushes on their head connection
(the same pipe that carries pull replies), so delivery needs no polling;
in-head (driver) subscribers are fed directly on the scheduler loop.
"""

from __future__ import annotations

import queue as _queue
from typing import Any, Iterator, Optional

import cloudpickle


class Subscription:
    """One subscriber of one channel. Not thread-safe across concurrent
    ``get`` calls (each message goes to exactly one getter)."""

    def __init__(self, channel: str, q, rt):
        self.channel = channel
        self._q = q
        self._rt = rt
        self._closed = False

    def get(self, timeout: Optional[float] = None) -> Any:
        """Next message (deserialized). Raises ``queue.Empty`` on timeout."""
        if timeout is None:
            blob = self._q.get()
        else:
            blob = self._q.get(timeout=timeout)
        return cloudpickle.loads(blob)

    def get_nowait(self) -> Any:
        return cloudpickle.loads(self._q.get_nowait())

    def __iter__(self) -> Iterator[Any]:
        while not self._closed:
            yield self.get()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._rt.pubsub_unsubscribe(self.channel, self._q)
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def publish(channel: str, message: Any) -> None:
    """Publish to every current subscriber of ``channel``."""
    from ray_tpu._private.worker import get_runtime

    get_runtime().pubsub_publish(str(channel), cloudpickle.dumps(message))


def subscribe(channel: str) -> Subscription:
    """Subscribe to ``channel``; messages published AFTER this call are
    delivered to the returned ``Subscription``."""
    from ray_tpu._private.worker import get_runtime

    rt = get_runtime()
    return Subscription(str(channel), rt.pubsub_subscribe(str(channel)), rt)


_queue_Empty = _queue.Empty  # re-export convenience for callers
