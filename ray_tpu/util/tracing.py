"""Distributed trace-context propagation across task/actor boundaries.

Parity: ``python/ray/util/tracing/tracing_helper.py`` (``:34``,
``_DictPropagator:165``) — the caller's span context travels with every task
spec and is adopted in the executing worker, so spans form one tree across
processes. The reference delegates to OpenTelemetry; this environment has no
OTel package, so the context model (16-byte trace id, 8-byte span ids,
parent links) is implemented natively.

Tracing-plane extension beyond the reference helper: a ``(trace_id,
span_id)`` is minted at every ENTRY POINT — driver ``remote()`` calls, serve
proxy requests, job submissions — and each task/actor call gets its span id
assigned at SUBMISSION time (``for_submission``), so the scheduler's
head-side lifecycle events and the executing worker's events land on the
SAME span. Nested submissions become children of the executing task's span.
The default is governed by the ``tracing_enabled`` config flag (on);
``enable_tracing``/``disable_tracing`` override per process.

The resulting span tree is queried with ``ray_tpu.trace(trace_id)`` /
``ray_tpu trace <id>`` (see ``ray_tpu._private.trace``).
"""

from __future__ import annotations

import os
import random
import threading
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

_CTX_KEY = "_trace_ctx"

# None = follow the runtime config (tracing_enabled, default on);
# True/False = explicit per-process override via enable/disable_tracing()
_enabled_override: Optional[bool] = None
_local = threading.local()

# id minting: urandom-seeded per-process PRNG — ~5x cheaper than os.urandom
# per call. Fork safety via os.register_at_fork (no per-call getpid syscall
# or lock on the submission hot path); getrandbits itself is GIL-atomic
_rng = random.Random(os.urandom(16))
try:
    os.register_at_fork(after_in_child=lambda: _rng.seed(os.urandom(16)))
except AttributeError:  # non-posix: spawn re-imports the module anyway
    pass
_randbits = _rng.getrandbits


def _ids(nbits: int) -> str:
    return "%0*x" % (nbits // 4, _randbits(nbits))


@dataclass
class TraceContext:
    trace_id: str  # 32 hex chars
    span_id: str   # 16 hex chars
    parent_id: Optional[str] = None
    # verbose = explicit-tracing mode (enable_tracing()): workers record a
    # per-task PROFILE wrapper span for chrome-timeline flow links. The
    # default-on plane leaves it False — lifecycle events carry the span
    # ids, sparing one telemetry span per task on the hot path. Inherited
    # by nested submissions so a whole traced call tree stays verbose.
    verbose: bool = False

    def to_dict(self) -> Dict[str, str]:
        d = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            d["parent_id"] = self.parent_id
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, str]) -> "TraceContext":
        return cls(d["trace_id"], d["span_id"], d.get("parent_id"))

    def to_tuple(self):
        if self.verbose:
            return (self.trace_id, self.span_id, self.parent_id, True)
        return (self.trace_id, self.span_id, self.parent_id)

    @classmethod
    def from_tuple(cls, t) -> "TraceContext":
        return cls(
            t[0],
            t[1],
            t[2] if len(t) > 2 else None,
            bool(t[3]) if len(t) > 3 else False,
        )


def enable_tracing() -> None:
    """Parity: ``ray start --tracing-startup-hook`` turning span export on.
    Overrides the ``tracing_enabled`` config flag in this process."""
    global _enabled_override
    _enabled_override = True


def disable_tracing() -> None:
    global _enabled_override
    _enabled_override = False


def reset_tracing() -> None:
    """Back to config-driven behavior (tests)."""
    global _enabled_override
    _enabled_override = None


# (runtime identity, resolved flag): the config is immutable per runtime,
# so the lookup chain runs once per connect, not per remote() call
_enabled_cache: Tuple[Optional[object], bool] = (None, False)


def tracing_enabled() -> bool:
    global _enabled_cache
    if _enabled_override is not None:
        return _enabled_override
    # config default: tracing rides the telemetry plane, so an unconnected
    # process (or telemetry off) reads as disabled
    try:
        from ray_tpu._private import worker as worker_mod

        rt = worker_mod._worker_runtime or worker_mod._driver
        if rt is None:
            return False
        cached_rt, val = _enabled_cache
        if rt is cached_rt:
            return val
        cfg = getattr(rt, "config", None)
        val = bool(getattr(cfg, "tracing_enabled", True)) and bool(
            getattr(cfg, "telemetry_enabled", True)
        )
        _enabled_cache = (rt, val)
        return val
    except Exception:
        return False


def get_current_context() -> Optional[TraceContext]:
    return getattr(_local, "ctx", None)


def _set_current_context(ctx: Optional[TraceContext]) -> None:
    _local.ctx = ctx


def _new_id(nbytes: int) -> str:
    return _ids(nbytes * 8)


def new_root() -> TraceContext:
    """A fresh root span (new trace id, no parent)."""
    return TraceContext(trace_id=_ids(128), span_id=_ids(64))


def start_span() -> TraceContext:
    """Begin a span under the current context (new trace if none) and make
    it current. Legacy surface — entry points prefer ``activate``/``scope``."""
    cur = get_current_context()
    if cur is None:
        ctx = new_root()
        ctx.verbose = _enabled_override is True
    else:
        ctx = TraceContext(
            trace_id=cur.trace_id,
            span_id=_ids(64),
            parent_id=cur.span_id,
            verbose=cur.verbose or _enabled_override is True,
        )
    _set_current_context(ctx)
    return ctx


def for_submission():
    """The submitted task's OWN context, minted at the call site so the
    scheduler's head-side events and the worker's execution events share one
    span id. Child of the caller's active span; a fresh root when this
    process has no active context and tracing is enabled; ``None`` (untraced
    task) otherwise. Does NOT change the caller's current context.

    Returns a compact ``(trace_id, span_id, parent_id)`` tuple for
    ``TaskSpec.trace_ctx`` (None when untraced).
    """
    cur = get_current_context()
    if cur is not None:
        # an active context propagates even in processes that never enabled
        # tracing — workers executing a traced task must keep the chain for
        # nested submissions (the reference achieves this via a cluster-wide
        # tracing startup hook on every worker)
        if cur.verbose or _enabled_override is True:
            return (cur.trace_id, _ids(64), cur.span_id, True)
        return (cur.trace_id, _ids(64), cur.span_id)
    if not tracing_enabled():
        return None
    if _enabled_override is True:
        return (_ids(128), _ids(64), None, True)
    return (_ids(128), _ids(64), None)


def activate(ctx: Optional[TraceContext]) -> None:
    """Make ``ctx`` the calling thread's current context."""
    _set_current_context(ctx)


def activate_from_spec(spec) -> Optional[TraceContext]:
    """Executing-worker side: adopt the task's submission-minted span as the
    current context (nested submissions become its children). Falls back to
    the legacy runtime_env side channel (older callers / user-injected
    contexts), where a child span is minted as before."""
    t = getattr(spec, "trace_ctx", None)
    if t is not None:
        ctx = TraceContext.from_tuple(t)
        _set_current_context(ctx)
        return ctx
    return extract_and_activate(getattr(spec, "runtime_env", None))


class scope:
    """``with tracing.scope(ctx):`` — activate a context for a block,
    restoring the previous one on exit (serve proxy / direct-plane server
    threads handle many requests on one thread)."""

    def __init__(self, ctx: Optional[TraceContext]):
        self._ctx = ctx
        self._prev: Optional[TraceContext] = None

    def __enter__(self):
        self._prev = get_current_context()
        _set_current_context(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        # only restore when this thread still holds the context we set: a
        # generator-held scope can be closed (GC) from a DIFFERENT thread,
        # and blindly restoring would clobber that thread's live context
        if get_current_context() is self._ctx:
            _set_current_context(self._prev)
        return False


def inject(runtime_env: Optional[dict]) -> Optional[dict]:
    """Attach the caller's context to an outgoing task spec via the
    runtime_env side channel (legacy path; new callers set
    ``TaskSpec.trace_ctx`` from :func:`for_submission` instead — the side
    channel forces the runtime-env apply path in the worker).

    Parity: ``_DictPropagator.inject_current_context``.
    """
    ctx = get_current_context()
    if ctx is None:
        if _enabled_override is not True:
            return runtime_env
        ctx = start_span()
    out = dict(runtime_env or {})
    out[_CTX_KEY] = ctx.to_dict()
    return out


def extract_and_activate(runtime_env: Optional[dict]) -> Optional[TraceContext]:
    """Legacy executing-worker side: adopt the caller's context as parent and
    open a child span for this task. Returns the new context (None if
    untraced)."""
    if not runtime_env or _CTX_KEY not in runtime_env:
        return None
    parent = TraceContext.from_dict(runtime_env[_CTX_KEY])
    child = TraceContext(
        trace_id=parent.trace_id,
        span_id=_ids(64),
        parent_id=parent.span_id,
        verbose=True,  # the side channel IS the legacy explicit-tracing path
    )
    _set_current_context(child)
    return child


def deactivate() -> None:
    _set_current_context(None)


def current_trace_id() -> Optional[str]:
    """The active trace id (e.g. to log alongside an external request id)."""
    ctx = get_current_context()
    return ctx.trace_id if ctx is not None else None


def context_args() -> Dict[str, str]:
    """The active context as chrome-trace/span args ({} when untraced) —
    the telemetry plane stamps these onto profile spans so timeline
    consumers can rebuild the parent-linked tree across processes."""
    ctx = get_current_context()
    return ctx.to_dict() if ctx is not None else {}
