"""Distributed trace-context propagation across task/actor boundaries.

Parity: ``python/ray/util/tracing/tracing_helper.py`` (``:34``,
``_DictPropagator:165``) — when tracing is enabled, the caller's span context
is injected into every task spec (runtime_env side channel) and extracted in
the executing worker, so spans form one tree across processes. The reference
delegates to OpenTelemetry; this environment has no OTel package, so the
context model (16-byte trace id, 8-byte span ids, parent links) is
implemented natively and spans land in the task timeline
(``ray_tpu.timeline()``) via the profiling event plane.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Dict, Optional

_CTX_KEY = "_trace_ctx"

_enabled = False
_local = threading.local()


@dataclass
class TraceContext:
    trace_id: str  # 32 hex chars
    span_id: str   # 16 hex chars
    parent_id: Optional[str] = None

    def to_dict(self) -> Dict[str, str]:
        d = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            d["parent_id"] = self.parent_id
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, str]) -> "TraceContext":
        return cls(d["trace_id"], d["span_id"], d.get("parent_id"))


def enable_tracing() -> None:
    """Parity: ``ray start --tracing-startup-hook`` turning span export on."""
    global _enabled
    _enabled = True


def disable_tracing() -> None:
    global _enabled
    _enabled = False


def tracing_enabled() -> bool:
    return _enabled


def get_current_context() -> Optional[TraceContext]:
    return getattr(_local, "ctx", None)


def _set_current_context(ctx: Optional[TraceContext]) -> None:
    _local.ctx = ctx


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def start_span() -> TraceContext:
    """Begin a span under the current context (new trace if none)."""
    cur = get_current_context()
    if cur is None:
        ctx = TraceContext(trace_id=_new_id(16), span_id=_new_id(8))
    else:
        ctx = TraceContext(
            trace_id=cur.trace_id, span_id=_new_id(8), parent_id=cur.span_id
        )
    _set_current_context(ctx)
    return ctx


def inject(runtime_env: Optional[dict]) -> Optional[dict]:
    """Attach the caller's context to an outgoing task spec (submission side).

    Parity: ``_DictPropagator.inject_current_context``.
    """
    ctx = get_current_context()
    if ctx is None:
        if not _enabled:
            return runtime_env
        ctx = start_span()
    # note: an active context propagates even in processes that never called
    # enable_tracing() — workers executing a traced task must keep the chain
    # for nested submissions (the reference achieves this via a cluster-wide
    # tracing startup hook on every worker)
    out = dict(runtime_env or {})
    out[_CTX_KEY] = ctx.to_dict()
    return out


def extract_and_activate(runtime_env: Optional[dict]) -> Optional[TraceContext]:
    """Executing-worker side: adopt the caller's context as parent and open a
    child span for this task. Returns the new context (None if untraced)."""
    if not runtime_env or _CTX_KEY not in runtime_env:
        return None
    parent = TraceContext.from_dict(runtime_env[_CTX_KEY])
    child = TraceContext(
        trace_id=parent.trace_id, span_id=_new_id(8), parent_id=parent.span_id
    )
    _set_current_context(child)
    return child


def deactivate() -> None:
    _set_current_context(None)


def context_args() -> Dict[str, str]:
    """The active context as chrome-trace/span args ({} when untraced) —
    the telemetry plane stamps these onto profile spans so timeline
    consumers can rebuild the parent-linked tree across processes."""
    ctx = get_current_context()
    return ctx.to_dict() if ctx is not None else {}
