"""State API: typed listers over live cluster state.

Parity: ``python/ray/util/state/api.py`` (``list_tasks``, ``list_actors``,
``list_objects``, ``list_nodes``, ``list_workers``, ``summarize_tasks``)
backed by the scheduler's task-event buffer and tables (the reference's
``GcsTaskManager`` + ``state_aggregator.py``).
"""

from ray_tpu.util.state.api import (
    backlog_summary,
    get_log,
    job_latency,
    launch_profile,
    list_actors,
    list_checkpoints,
    list_cluster_events,
    list_decisions,
    list_jobs,
    list_links,
    list_logs,
    list_nodes,
    list_objects,
    list_objects_page,
    list_placement_groups,
    list_tasks,
    list_traces,
    list_train_runs,
    list_transfers,
    list_workers,
    summarize_objects,
    summarize_tasks,
    summarize_transfers,
    train_run,
)

__all__ = [
    "backlog_summary",
    "list_tasks",
    "list_actors",
    "list_checkpoints",
    "list_objects",
    "list_objects_page",
    "summarize_objects",
    "list_nodes",
    "list_workers",
    "list_placement_groups",
    "list_cluster_events",
    "list_decisions",
    "list_jobs",
    "list_logs",
    "list_traces",
    "list_train_runs",
    "list_links",
    "list_transfers",
    "summarize_transfers",
    "train_run",
    "job_latency",
    "launch_profile",
    "get_log",
    "summarize_tasks",
]
