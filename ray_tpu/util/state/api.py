"""Typed state listers (see package docstring)."""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.worker import get_runtime


def _rpc(op: str, *args):
    rt = get_runtime()
    if hasattr(rt, "scheduler_rpc"):
        return rt.scheduler_rpc(op, args)
    return rt.rpc(op, *args)


def _compare(op: str, have, value) -> bool:
    """One filter predicate. ``=``/``!=`` compare raw; the ordering
    operators compare numerically (parity: the reference state API's
    ``<``/``>``/``<=``/``>=`` on numeric columns) and a non-numeric or
    missing field never matches an ordering filter."""
    if op == "=":
        return have == value
    if op == "!=":
        return have != value
    if op not in ("<", ">", "<=", ">="):
        raise ValueError(f"unsupported filter operator {op!r}")
    try:
        a, b = float(have), float(value)
    except (TypeError, ValueError):
        return False
    if op == "<":
        return a < b
    if op == ">":
        return a > b
    if op == "<=":
        return a <= b
    return a >= b


def _filtered(rows: List[dict], filters) -> List[dict]:
    if not filters:
        return rows
    return [
        row
        for row in rows
        if all(_compare(op, row.get(key), value) for key, op, value in filters)
    ]


def _list(op: str, filters, limit: int) -> List[dict]:
    # limit is pushed INTO the rpc: the server truncates at the source, so
    # a LIMIT-10 query against a 10k-task cluster never serializes 10k
    # rows. Client-side filters then apply to the capped fetch (same
    # contract as the reference: limit bounds rows *examined*).
    return _filtered(_rpc(op, limit), filters)[:limit]


def list_tasks(filters=None, limit: int = 10_000) -> List[dict]:
    return _list("list_tasks", filters, limit)


def list_actors(filters=None, limit: int = 10_000) -> List[dict]:
    return _list("list_actors", filters, limit)


def list_workers(filters=None, limit: int = 10_000) -> List[dict]:
    return _list("list_workers", filters, limit)


def list_nodes(filters=None, limit: int = 10_000) -> List[dict]:
    return _list("list_nodes", filters, limit)


def _flush_for_read(cluster: bool = True) -> None:
    """Read-your-writes for memory-plane reads: provenance records ride
    telemetry batches, so pull buffered batches first (in-process driver
    only; remote drivers accept one interval of lag). ``cluster=False``
    drains only THIS process — polling consumers (the dashboard's 2s
    tick) must not fan a flush broadcast out to every worker per poll
    (same rationale as the /api/trace handler)."""
    rt = get_runtime()
    if hasattr(rt, "scheduler"):
        from ray_tpu._private import telemetry

        telemetry.flush()
        if cluster:
            try:
                rt.scheduler.request_telemetry_flush()
            except Exception:
                pass


def list_objects(filters=None, limit: int = 10_000) -> List[dict]:
    """Live objects with allocation provenance (memory plane): one row per
    object with ``size_bytes`` / ``ref_count`` / ``callsite`` / ``kind`` /
    ``job`` / ``task`` / ``class`` / ``age_s`` / ``trace_id``. Filters AND
    the row cap run server-side (the PR-2 pushdown contract) — see
    :func:`list_objects_page` for the truncation flag."""
    return list_objects_page(filters, limit)["rows"]


def list_objects_page(
    filters=None, limit: int = 10_000, *, cluster_flush: bool = True
) -> dict:
    """``{"rows": [...], "truncated": bool, "total": matched}`` — the raw
    server reply. ``truncated`` means more rows matched than the (hard-
    capped) limit returned; ``total`` counts every match examined."""
    _flush_for_read(cluster=cluster_flush)
    return _rpc("list_objects", limit, filters)


def summarize_objects(
    group_by: str = "callsite", limit: int = 50, *, cluster_flush: bool = True
) -> dict:
    """Server-side grouping of live objects by creation ``callsite`` /
    ``job`` / ``node`` (parity: ``ray memory``'s group-by views): rows
    carry live count+bytes, the ref-holder classification split (IN_USE /
    PINNED_BY_DEAD_OWNER / CAPTURED_IN_ACTOR / LEAK_SUSPECT), exemplar
    object ids, and a ``leak_suspect`` flag from the watchdog; plus store
    usage (sealed vs unsealed vs capacity, high-water) and the current
    leak-suspect table."""
    _flush_for_read(cluster=cluster_flush)
    return _rpc("summarize_objects", group_by, limit)


def list_placement_groups(filters=None, limit: int = 10_000) -> List[dict]:
    return _list("list_placement_groups", filters, limit)


def summarize_tasks() -> Dict[str, Dict[str, int]]:
    return _rpc("summarize_tasks")


def backlog_summary() -> dict:
    """Per-resource-shape scheduler backlog: ``{"shapes": [{"shape",
    "queued", "leased", "node_backlog"}], "pg_pending": [bundle, ...]}``.
    ``queued`` counts tasks in the head's sharded ready queue, ``leased``
    tasks handed to node-local dispatchers, ``node_backlog`` the leased
    subset still parked in a node's local queue. The autoscaler's demand
    input; surfaced by ``ray_tpu status --backlog``."""
    return _rpc("backlog_summary")


def list_cluster_events(
    filters=None,
    limit: int = 10_000,
    job_id: Optional[str] = None,
    after_event_id: Optional[int] = None,
    since_ts: Optional[float] = None,
) -> List[dict]:
    """Structured cluster events — WORKER_DIED, NODE_DEAD, TASK_RETRY,
    TASK_FAILED, LEASE_FAILED, OBJECT_LOST, OOM, PREEMPTED, STRAGGLER,
    JOB_QUEUED/ADMITTED/REJECTED, ... — in chronological order (parity:
    ``ray.util.state.list_cluster_events``). ``job_id=`` (job hex) keeps
    only events attributed to that job — matching an explicit ``job_id``
    field or the job embedded in the event's task/actor id; the filter
    runs server-side, so the cap applies after it. ``after_event_id=`` is
    a server-side tail cursor (only events beyond that id — the backbone
    of ``ray_tpu events --follow``); ``since_ts=`` keeps events at or
    after a wall timestamp. Flushes the telemetry plane first so
    worker/serve-recorded events are read-your-writes."""
    rt = get_runtime()
    if hasattr(rt, "scheduler"):
        from ray_tpu._private import telemetry

        telemetry.flush()
        try:
            rt.scheduler.request_telemetry_flush()
        except Exception:
            pass
    return _filtered(
        _rpc("list_cluster_events", limit, job_id, after_event_id, since_ts),
        filters,
    )[:limit]


def list_incidents(
    filters=None,
    limit: int = 1000,
    state: Optional[str] = None,
    kind: Optional[str] = None,
) -> List[dict]:
    """Alerting-plane incident summaries, newest first: ``{id, kind,
    subject, state (open|closed), severity, source (watchdog|slo), slo,
    opened_at, closed_at, duration_s, count, planes, verdict}``.
    ``state=``/``kind=`` filter server-side; ``filters=`` applies the
    standard client-side tuples on top. The full record (cross-plane
    digest included) comes from :func:`get_incident`."""
    return _filtered(_rpc("list_incidents", limit, state, kind), filters)[
        :limit
    ]


def get_incident(incident_id: str) -> Optional[dict]:
    """One incident's full record: the summary fields plus the trigger
    events and the cross-plane ``digest`` (correlated cluster events,
    exemplar traces with stage breakdowns, memory snapshot, link-ledger /
    goodput-ledger / decision-ring slices — ``digest["planes"]`` lists
    the non-empty sections). Open incidents re-join the planes at read
    time, so the view is live."""
    return _rpc("incident", incident_id)


def list_slos(filters=None, limit: int = 1000) -> List[dict]:
    """Registered SLOs with live burn-rate status: the spec fields plus
    ``subjects`` (observed subject count), ``ok``, ``breaches_total``,
    and ``worst`` (the worst subject's fast/slow burns + detail)."""
    return _filtered(_rpc("list_slos"), filters)[:limit]


def register_slo(
    name: str,
    kind: str,
    target: float,
    **kwargs,
) -> dict:
    """Register (or replace) one declarative SLO. ``kind`` is one of
    ``job_latency_p99`` / ``deployment_latency_p99`` /
    ``deployment_availability`` / ``deployment_ttft_p99`` /
    ``train_goodput_floor`` / ``link_throughput_floor`` /
    ``actor_launch_rate_floor``; keyword extras: ``budget`` (tolerated
    bad fraction, default 0.1), ``threshold`` (burn multiple, default
    1.0), ``fast_window_s``/``slow_window_s`` (multi-window burn-rate
    evaluation), ``subject`` (None = every observed subject),
    ``severity``, ``params``. Evaluated at 1 Hz on the scheduler's
    maintenance pass; a breach opens an incident."""
    return _rpc(
        "register_slo",
        {"name": name, "kind": kind, "target": target, **kwargs},
    )


def remove_slo(name: str) -> bool:
    return _rpc("remove_slo", name)


def doctor() -> dict:
    """One-shot cluster health digest (the ``ray_tpu doctor`` payload):
    ``healthy``, open incidents, recently-closed verdicts, SLO status,
    top event counts, watchdog totals, and the store snapshot. Flushes
    the telemetry plane first for a current view."""
    _flush_for_read()
    return _rpc("doctor")


def list_jobs(filters=None, limit: int = 10_000) -> List[dict]:
    """The multi-tenant job plane's arbitration rows: one per job the
    scheduler has seen, with ``priority`` / ``weight`` / ``quota`` /
    live ``usage`` (+ ``object_store_bytes``) / ``running`` / ``ready`` /
    ``admission`` (ADMITTED | QUEUED | REJECTED) / ``queue_position`` in
    the admission queue / ``preemptions`` / ``oom_kills``. Submission
    metadata (entrypoint etc.) rides in ``meta`` for jobs registered via
    ``JobSubmissionClient``."""
    return _list("list_jobs", filters, limit)


def list_decisions(filters=None, limit: int = 1000, kind: str = "") -> List[dict]:
    """Control-plane decision flight recorder (bounded ring): scheduler
    placement decisions (actor, winning node, reason, queue wait) and
    autoscaler reconcile decisions (demand seen, to_launch delta,
    launched/terminated, why — ``backlog_demand`` / ``cooldown_active`` /
    ``serves_backlog`` / ``upscaling_speed_cap`` / ``idle_timeout``), in
    record order with monotonically increasing ``seq``. ``kind=`` keeps
    only ``placement`` or ``autoscaler`` rows (server-side); client-side
    ``filters`` then apply."""
    return _filtered(_rpc("list_decisions", limit, kind or None), filters)[
        :limit
    ]


def launch_profile(limit: int = 50) -> dict:
    """Actor-launch lifecycle profile (control-plane observability):
    per-stage count/mean/p50/p95/max over the recent-launch ring
    (``submit`` → ``placement`` → ``worker_spawn`` → ``execute`` plus
    worker-reported ``runtime_env`` / ``actor_class_load``), cumulative
    stage-seconds, worker boot-stage seconds, and the most recent
    ``limit`` launch records with their trace ids. Flushes telemetry
    first so worker-side creation stages are read-your-writes."""
    _flush_for_read(cluster=True)
    return _rpc("launch_profile", int(limit))


def list_traces(limit: int = 100) -> List[dict]:
    """Recent request traces (tracing plane), newest first: one digest per
    trace id (``first_time`` / ``last_time`` / ``root`` / ``events``).
    Drill into one with ``ray_tpu.trace(trace_id)``."""
    return _rpc("list_traces", int(limit))


def job_latency() -> Dict[str, dict]:
    """Per-job sliding-window latency quantiles (p50/p95/p99 + exemplar
    trace ids), keyed by job id hex."""
    return _rpc("job_latency")


def list_train_runs() -> List[dict]:
    """Training runs in the step plane's bounded index, newest first: one
    digest per run (world size, steps seen, recompiles, live goodput,
    attributed downtime seconds, data-wait ratio, max rank skew, status).
    Drill into one with :func:`train_run` or ``ray_tpu.train_timeline``.
    Mid-run, step records lag at most one executor publish interval
    (``train_goodput_publish_interval_s``); a finished fit() has pushed
    everything."""
    _flush_for_read(cluster=True)
    return _rpc("list_train_runs")


def train_run(run: str, max_steps: Optional[int] = None) -> Optional[dict]:
    """One training run's full step-time attribution: per-step per-rank
    stage records (``data_wait`` / ``host_to_device`` / ``compile`` /
    ``compute`` / ``collective_wait`` with the straggler rank /
    ``checkpoint_stall`` / ``other``), run-level stage totals, per-operator
    ingest stalls, and the executor's goodput + downtime ledger. ``None``
    when the run is unknown."""
    _flush_for_read(cluster=True)
    return _rpc("train_run", run, max_steps)


def list_links(filters=None, limit: int = 10_000) -> List[dict]:
    """Transfer plane: the scheduler's per-(src, dst, path) link ledger —
    one row per link with cumulative ``bytes`` / ``transfers`` /
    ``failures`` / ``stalls``, live ``inflight`` count, throughput
    ``ewma_gib_per_s``, relay ``max_hop``, and the watchdog's ``slow``
    flag. Paths: ``socket`` | ``shm_peer`` | ``spill`` | ``relay``.
    Flushes worker-side read records first (same freshness contract as
    :func:`list_transfers`)."""
    _flush_for_read(cluster=True)
    return _filtered(_rpc("list_links", limit), filters)[:limit]


def list_transfers(limit: int = 100) -> List[dict]:
    """Recent completed transfers (bounded ring), newest first: one record
    per transfer with its stage decomposition (``dial`` → ``request`` →
    ``first_byte_wait`` → ``wire`` → ``seal`` in ms), bytes/chunks,
    GiB/s, relay hop, owning job, and the requester's trace id (drill in
    with ``ray_tpu.trace``). Flushes worker-side read records first."""
    _flush_for_read(cluster=True)
    return _rpc("list_transfers", int(limit))


def summarize_transfers(
    group_by: str = "link", limit: int = 50, *, cluster_flush: bool = True
) -> dict:
    """Server-side transfer grouping (transfer plane): ``link`` (src->dst
    with per-path byte split + throughput), ``path`` (fleet totals +
    stage-seconds), ``job`` (per-owning-job inter-node bytes), or ``task``
    (producing task name — ``data:<stage>`` rows give ray_tpu.data its
    per-operator cross-node bytes). The header carries fleet counters:
    inflight / retries / stalled / leaked buffers / slow-link events."""
    _flush_for_read(cluster=cluster_flush)
    return _rpc("summarize_transfers", group_by, limit)


def list_checkpoints(filters=None, limit: int = 10_000) -> List[dict]:
    """Checkpoints of every run registered with the checkpoint plane
    (``ray_tpu.train.checkpointing``): one row per checkpoint prefix with
    ``run`` / ``step`` / ``committed`` / ``path`` (+ manifest metadata for
    committed ones). The registry lives in the GCS KV; the storage scan
    happens caller-side so ``memory://`` test backends resolve in the
    calling process. Uncommitted rows are in-flight or crashed saves —
    readers (``latest``, ``Checkpoint.from_uri``) never restore them."""
    from ray_tpu.train import checkpointing

    rows: List[dict] = []
    for entry in checkpointing.registered_runs():
        by_step: Dict[int, dict] = {}
        for base_key, base in (
            ("local", entry.get("local_base")),
            ("storage", entry.get("storage_uri")),
        ):
            if not base:
                continue
            for row in checkpointing.list_checkpoints(base):
                row["run"] = entry.get("run")
                row["location"] = base_key
                cur = by_step.get(row["step"])
                # one logical row per step per run; a COMMITTED copy in
                # either location wins over an uncommitted one (e.g. a
                # half-GC'd local dir with an intact storage mirror)
                if cur is None or (row["committed"] and not cur["committed"]):
                    by_step[row["step"]] = row
        rows.extend(by_step.values())
    rows.sort(key=lambda r: (r.get("run") or "", -(r.get("step") or 0)))
    return _filtered(rows, filters)[:limit]


def _session_logs_dir() -> str:
    import os

    from ray_tpu._private.worker import get_driver

    d = get_driver()
    if d is None or not hasattr(d, "node"):
        raise RuntimeError(
            "list_logs/get_log read the session's log directory and are "
            "driver-only (call them from the process that ran ray_tpu.init)"
        )
    return os.path.join(d.node.session_dir, "logs")


def list_logs(limit: int = 10_000) -> List[dict]:
    """Session log files (parity: ``ray.util.state.list_logs`` over the
    session's logs dir)."""
    import glob
    import os

    logs_dir = _session_logs_dir()
    out = []
    # skip directories (spill/, runtime env dirs) BEFORE applying the
    # limit, or a handful of subdirectories could mask every real file
    for path in sorted(glob.glob(os.path.join(logs_dir, "*"))):
        if not os.path.isfile(path):
            continue
        st = os.stat(path)
        out.append({"filename": os.path.basename(path), "path": path,
                    "size_bytes": st.st_size, "mtime": st.st_mtime})
        if len(out) >= limit:
            break
    return out


def get_log(
    filename: str = "",
    *,
    task_id: str = "",
    tail: int = 1000,
) -> str:
    """Read (the tail of) one session log file, or — with ``task_id=`` —
    every persisted worker-log line attributed to that task, across all
    worker files (the structured-log plane tags each line with the task id
    that printed it, threaded actors included)."""
    import collections
    import glob
    import os

    logs_dir = _session_logs_dir()
    if task_id:
        # read-your-writes: pull workers' buffered log batches first
        from ray_tpu._private.worker import get_driver

        try:
            get_driver().scheduler.request_telemetry_flush()
        except Exception:
            pass
        needle = f"task={task_id}"
        hits: List[str] = []
        for path in sorted(glob.glob(os.path.join(logs_dir, "worker-*"))):
            if not os.path.isfile(path):
                continue
            with open(path, errors="replace") as fh:
                hits.extend(line for line in fh if needle in line)
        return "".join(hits[-tail:])
    if not filename:
        raise ValueError("get_log() needs a filename or a task_id")
    path = os.path.join(logs_dir, os.path.basename(filename))
    with open(path, errors="replace") as fh:
        return "".join(collections.deque(fh, maxlen=tail))
