"""Typed state listers (see package docstring)."""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu._private.worker import get_runtime


def _rpc(op: str, *args):
    rt = get_runtime()
    if hasattr(rt, "scheduler_rpc"):
        return rt.scheduler_rpc(op, args)
    return rt.rpc(op, *args)


def _filtered(rows: List[dict], filters) -> List[dict]:
    if not filters:
        return rows
    out = []
    for row in rows:
        ok = True
        for key, op, value in filters:
            have = row.get(key)
            if op == "=" and have != value:
                ok = False
            elif op == "!=" and have == value:
                ok = False
        if ok:
            out.append(row)
    return out


def list_tasks(filters=None, limit: int = 10_000) -> List[dict]:
    return _filtered(_rpc("list_tasks"), filters)[:limit]


def list_actors(filters=None, limit: int = 10_000) -> List[dict]:
    return _filtered(_rpc("list_actors"), filters)[:limit]


def list_workers(filters=None, limit: int = 10_000) -> List[dict]:
    return _filtered(_rpc("list_workers"), filters)[:limit]


def list_nodes(filters=None, limit: int = 10_000) -> List[dict]:
    return _filtered(_rpc("list_nodes"), filters)[:limit]


def list_objects(filters=None, limit: int = 10_000) -> List[dict]:
    return _filtered(_rpc("list_objects"), filters)[:limit]


def list_placement_groups(filters=None, limit: int = 10_000) -> List[dict]:
    return _filtered(_rpc("list_placement_groups"), filters)[:limit]


def summarize_tasks() -> Dict[str, Dict[str, int]]:
    return _rpc("summarize_tasks")


def _session_logs_dir() -> str:
    import os

    from ray_tpu._private.worker import get_driver

    d = get_driver()
    if d is None or not hasattr(d, "node"):
        raise RuntimeError(
            "list_logs/get_log read the session's log directory and are "
            "driver-only (call them from the process that ran ray_tpu.init)"
        )
    return os.path.join(d.node.session_dir, "logs")


def list_logs(limit: int = 10_000) -> List[dict]:
    """Session log files (parity: ``ray.util.state.list_logs`` over the
    session's logs dir)."""
    import glob
    import os

    logs_dir = _session_logs_dir()
    out = []
    for path in sorted(glob.glob(os.path.join(logs_dir, "*")))[:limit]:
        st = os.stat(path)
        out.append({"filename": os.path.basename(path), "path": path,
                    "size_bytes": st.st_size, "mtime": st.st_mtime})
    return out


def get_log(filename: str, *, tail: int = 1000) -> str:
    """Read (the tail of) one session log file."""
    import collections
    import os

    path = os.path.join(_session_logs_dir(), os.path.basename(filename))
    with open(path, errors="replace") as fh:
        return "".join(collections.deque(fh, maxlen=tail))
