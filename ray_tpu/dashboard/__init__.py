"""Dashboard: HTTP observability surface.

Parity: ``python/ray/dashboard`` (head process serving cluster state over
HTTP; SURVEY.md §2.2). The reference ships an aiohttp + React SPA; here a
stdlib HTTP server in the driver serves a dependency-free single-page UI
(``dashboard/ui.py``) over the same data as JSON:

  /                     single-page UI (tabs over every endpoint below)
  /overview             minimal static HTML overview
  /api/cluster_status   resources + nodes
  /api/tasks            task table            /api/actors     actor table
  /api/objects          object store          /api/jobs       job table
  /api/events           cluster event log (failure forensics)
  /api/incidents        alerting plane: incidents + SLO burn status
  /api/doctor           one-shot cluster health digest
  /api/launch           actor-launch lifecycle profile (control plane)
  /api/decisions        scheduler/autoscaler decision flight recorder
  /api/stacks           thread stacks of driver + every node daemon
                        (the reporter-agent py-spy role)
  /api/profiler/start|stop   jax.profiler XPlane device traces
  /metrics              Prometheus exposition
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

_server: Optional[ThreadingHTTPServer] = None


def start_dashboard(port: int = 8765) -> int:
    """Start the dashboard server in this (driver) process; returns port."""
    global _server
    if _server is not None:
        return _server.server_address[1]

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_GET(self):
            import ray_tpu
            from ray_tpu.util import state

            try:
                if self.path == "/api/cluster_status":
                    body = {
                        "total": ray_tpu.cluster_resources(),
                        "available": ray_tpu.available_resources(),
                        "nodes": state.list_nodes(),
                    }
                elif self.path == "/api/tasks":
                    body = state.list_tasks()
                elif self.path == "/api/actors":
                    body = state.list_actors()
                elif self.path == "/api/workers":
                    body = state.list_workers()
                elif self.path == "/api/objects":
                    # local flush only — 2s UI polling (see /api/memory)
                    body = state.list_objects_page(cluster_flush=False)[
                        "rows"
                    ]
                elif urlparse(self.path).path == "/api/memory":
                    # memory plane: live objects grouped server-side by
                    # callsite/job/node + store usage + leak suspects.
                    # Local flush only: the UI re-polls every 2s, and a
                    # cluster-wide flush fan-out per tick would hammer
                    # every worker (same rationale as /api/trace) —
                    # worker-side records lag at most one batch interval
                    q = parse_qs(urlparse(self.path).query)
                    body = state.summarize_objects(
                        group_by=q.get("group_by", ["callsite"])[0],
                        limit=int(q.get("limit", ["50"])[0]),
                        cluster_flush=False,
                    )
                elif self.path == "/api/placement_groups":
                    body = state.list_placement_groups()
                elif self.path == "/api/serve":
                    from ray_tpu import serve as serve_lib

                    try:
                        body = serve_lib.status()
                    except ValueError:
                        body = {}
                elif self.path == "/api/logs":
                    body = state.list_logs()
                elif urlparse(self.path).path == "/api/events":
                    # structured cluster events (failure forensics plane):
                    # WORKER_DIED, TASK_FAILED, STRAGGLER, OOM,
                    # PREEMPTED, JOB_QUEUED/ADMITTED/REJECTED, ...
                    q = parse_qs(urlparse(self.path).query)
                    limit = int(q.get("limit", ["500"])[0])
                    job_id = q.get("job_id", [None])[0]
                    body = state.list_cluster_events(
                        limit=limit, job_id=job_id
                    )
                elif urlparse(self.path).path == "/api/jobs":
                    # multi-tenant job plane: every arbitration row
                    # (priority / quota / usage / admission / queue
                    # position), plus submission records for jobs that
                    # came in through the JobSubmissionClient
                    from ray_tpu.job_submission import JobSubmissionClient

                    body = {
                        "jobs": state.list_jobs(),
                        "submissions": JobSubmissionClient().list_jobs(),
                    }
                elif self.path == "/api/event_stats":
                    from ray_tpu._private.worker import get_driver

                    body = get_driver().rpc("event_stats")
                elif self.path == "/api/runtime_metrics":
                    # scheduler internals as JSON series (the /metrics
                    # Prometheus exposition carries the same data as text)
                    from ray_tpu._private.worker import get_driver

                    body = get_driver().rpc("runtime_metrics")
                elif self.path == "/api/timeline":
                    body = ray_tpu.timeline()
                elif urlparse(self.path).path == "/api/traces":
                    # request-tracing plane: recent trace digests
                    q = parse_qs(urlparse(self.path).query)
                    body = ray_tpu.recent_traces(
                        limit=int(q.get("limit", ["100"])[0])
                    )
                elif urlparse(self.path).path == "/api/trace":
                    # one request's span tree + critical-path decomposition.
                    # Served from already-ingested events (local flush only):
                    # the UI re-polls this every 2s, and a cluster-wide
                    # flush fan-out per tick would hammer every worker —
                    # worker-side stages lag at most one telemetry interval
                    q = parse_qs(urlparse(self.path).query)
                    tid = q.get("id", [""])[0]
                    if tid:
                        from ray_tpu._private import telemetry as _tele
                        from ray_tpu._private.trace import build_trace
                        from ray_tpu._private.worker import get_driver

                        _tele.flush()
                        events = get_driver().rpc("trace_events", tid)
                        body = build_trace(events, tid).to_dict()
                    else:
                        body = {}
                elif urlparse(self.path).path == "/api/train":
                    # training step plane: run digests, or one run's
                    # per-rank step records + downtime ledger (?run=).
                    # Local flush only — 2s UI polling (the /api/trace
                    # rule); worker step records lag at most one telemetry
                    # batch interval
                    from ray_tpu._private import telemetry as _tele
                    from ray_tpu._private.worker import get_driver

                    _tele.flush()
                    q = parse_qs(urlparse(self.path).query)
                    run = q.get("run", [""])[0]
                    if run:
                        body = get_driver().rpc(
                            "train_run",
                            run,
                            int(q.get("max_steps", ["50"])[0]),
                        ) or {}
                    else:
                        body = get_driver().rpc("list_train_runs")
                elif urlparse(self.path).path == "/api/net":
                    # transfer plane: link ledger + recent transfer stage
                    # records + fleet summary (network tab). Local flush
                    # only — 2s UI polling (the /api/trace rule); worker
                    # read records lag at most one telemetry interval
                    from ray_tpu._private import telemetry as _tele
                    from ray_tpu._private.worker import get_driver

                    _tele.flush()
                    q = parse_qs(urlparse(self.path).query)
                    drv = get_driver()
                    body = {
                        "links": drv.rpc("list_links", 200),
                        "transfers": drv.rpc(
                            "list_transfers",
                            int(q.get("limit", ["50"])[0]),
                        ),
                        "summary": drv.rpc("summarize_transfers", "path", 20),
                    }
                elif urlparse(self.path).path == "/api/incidents":
                    # alerting plane: incident summaries + registered SLO
                    # burn status, plus one full digest when ?id= is given
                    # (head-side state, no worker flush needed)
                    from ray_tpu._private.worker import get_driver

                    q = parse_qs(urlparse(self.path).query)
                    drv = get_driver()
                    inc_id = q.get("id", [None])[0]
                    if inc_id:
                        body = drv.rpc("incident", inc_id)
                    else:
                        body = {
                            "incidents": drv.rpc(
                                "list_incidents",
                                int(q.get("limit", ["100"])[0]),
                                q.get("state", [None])[0],
                                None,
                            ),
                            "slos": drv.rpc("list_slos"),
                        }
                elif urlparse(self.path).path == "/api/doctor":
                    # one-shot health digest (`ray_tpu doctor` payload)
                    from ray_tpu._private.worker import get_driver

                    body = get_driver().rpc("doctor")
                elif urlparse(self.path).path == "/api/decisions":
                    # decision flight recorder: scheduler placement +
                    # autoscaler reconcile decisions (head-side ring, no
                    # worker flush needed)
                    from ray_tpu._private.worker import get_driver

                    q = parse_qs(urlparse(self.path).query)
                    body = get_driver().rpc(
                        "list_decisions",
                        int(q.get("limit", ["200"])[0]),
                        q.get("kind", [None])[0],
                    )
                elif urlparse(self.path).path == "/api/launch":
                    # actor-launch lifecycle profile. Local flush only —
                    # 2s UI polling (the /api/trace rule); worker-side
                    # creation stages lag at most one telemetry interval
                    from ray_tpu._private import telemetry as _tele
                    from ray_tpu._private.worker import get_driver

                    _tele.flush()
                    q = parse_qs(urlparse(self.path).query)
                    body = get_driver().rpc(
                        "launch_profile",
                        int(q.get("limit", ["50"])[0]),
                    )
                elif self.path == "/api/job_latency":
                    # per-job sliding-window p50/p95/p99 + exemplar traces
                    from ray_tpu._private.worker import get_driver

                    body = get_driver().rpc("job_latency")
                elif urlparse(self.path).path == "/api/flamegraph":
                    # aggregated profiler samples as a speedscope document
                    from ray_tpu._private import sampler as _sampler
                    from ray_tpu._private.worker import get_driver

                    q = parse_qs(urlparse(self.path).query)
                    _sampler.get_sampler().drain()
                    rows = get_driver().rpc(
                        "profile_samples",
                        q.get("task_id", [None])[0],
                        q.get("trace_id", [None])[0],
                    )
                    body = _sampler.speedscope_document(rows)
                elif self.path.startswith("/api/profiler/start"):
                    # device-trace capture (parity role: the reporter agent's
                    # py-spy/memray profiling endpoints; on TPU the profile of
                    # record is jax.profiler's XPlane trace)

                    import jax

                    q = parse_qs(urlparse(self.path).query)
                    logdir = q.get("logdir", ["/tmp/ray_tpu_jax_trace"])[0]
                    jax.profiler.start_trace(logdir)
                    body = {"status": "tracing", "logdir": logdir}
                elif self.path == "/api/profiler/stop":
                    import jax

                    jax.profiler.stop_trace()
                    body = {"status": "stopped"}
                elif self.path == "/api/node_stats":
                    # per-node reporter metrics (cpu/mem/store/workers),
                    # pushed on heartbeats (reporter_agent.py:314 role)
                    from ray_tpu._private.worker import get_driver

                    body = get_driver().rpc("node_stats")
                elif urlparse(self.path).path == "/api/profile":
                    # py-spy-style sampled stacks from every node daemon
                    # (exact path match: /api/profiler/* must not land here)
                    from ray_tpu._private.worker import get_driver

                    q = parse_qs(urlparse(self.path).query)
                    dur = float(q.get("duration", ["2.0"])[0])
                    drv = get_driver()
                    body = {}
                    if drv is not None and hasattr(drv, "node"):
                        body = drv.node.scheduler.request_node_stack_samples(
                            duration_s=min(dur, 30.0)
                        )
                elif self.path == "/api/stacks":
                    # live thread stacks: driver + every node daemon (the
                    # reporter-agent py-spy role, reporter_agent.py:314)
                    from ray_tpu._private.profiling import format_thread_stacks
                    from ray_tpu._private.worker import get_driver

                    body = {"driver": format_thread_stacks()}
                    drv = get_driver()
                    if drv is not None and hasattr(drv, "node"):
                        body.update(
                            drv.node.scheduler.request_node_stacks()
                        )
                elif self.path == "/metrics":
                    from ray_tpu.util.metrics import prometheus_text

                    blob = prometheus_text().encode()
                    self._reply(200, blob, "text/plain; version=0.0.4")
                    return
                elif self.path == "/":
                    from ray_tpu.dashboard.ui import PAGE

                    self._reply(200, PAGE.encode(), "text/html")
                    return
                elif self.path == "/overview":
                    blob = _overview_html().encode()
                    self._reply(200, blob, "text/html")
                    return
                else:
                    self._reply(404, b'{"error": "not found"}', "application/json")
                    return
                self._reply(200, json.dumps(body, default=str).encode(), "application/json")
            except Exception as e:  # noqa: BLE001
                self._reply(500, json.dumps({"error": str(e)}).encode(), "application/json")

        def do_PUT(self):
            # declarative serve deploy (parity: the REST API the reference's
            # `serve deploy` talks to: PUT /api/serve/applications/)
            import ray_tpu  # noqa: F401

            try:
                if self.path.rstrip("/") == "/api/serve/applications":
                    from ray_tpu import serve as serve_lib

                    length = int(self.headers.get("Content-Length") or 0)
                    config = json.loads(self.rfile.read(length))
                    handles = serve_lib.deploy_config(config)
                    body = {"deployed": sorted(handles)}
                    self._reply(200, json.dumps(body).encode(), "application/json")
                else:
                    self._reply(404, b'{"error": "not found"}', "application/json")
            except Exception as e:  # noqa: BLE001
                self._reply(
                    500, json.dumps({"error": str(e)}).encode(), "application/json"
                )

        def _reply(self, code: int, blob: bytes, ctype: str):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

    _server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=_server.serve_forever, daemon=True).start()
    return _server.server_address[1]


def stop_dashboard():
    global _server
    if _server is not None:
        _server.shutdown()
        _server = None


def _overview_html() -> str:
    import ray_tpu
    from ray_tpu.util import state

    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    rows = "".join(
        f"<tr><td>{k}</td><td>{avail.get(k, 0):.1f}</td><td>{v:.1f}</td></tr>"
        for k, v in sorted(total.items())
    )
    summary = state.summarize_tasks()
    tasks = "".join(
        f"<tr><td>{name}</td><td>{counts}</td></tr>" for name, counts in summary.items()
    )
    return f"""<html><head><title>ray_tpu dashboard</title></head><body>
<h1>ray_tpu</h1>
<h2>Resources</h2>
<table border=1><tr><th>resource</th><th>available</th><th>total</th></tr>{rows}</table>
<h2>Tasks</h2>
<table border=1><tr><th>name</th><th>states</th></tr>{tasks}</table>
<p>APIs: /api/cluster_status /api/tasks /api/actors /api/workers /api/objects
/api/placement_groups /api/jobs /metrics</p>
</body></html>"""
