"""Dashboard single-page UI.

Parity role: the reference's React SPA (``python/ray/dashboard/client/``,
194 TS files) — scoped to a dependency-free static page (this environment is
zero-egress: no CDN, no build step) that polls the JSON endpoints the
dashboard already serves and renders the same panes: cluster, nodes, tasks,
actors, objects, placement groups, serve, jobs, logs, event stats, stacks.
"""

PAGE = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  :root { --bg:#10141a; --panel:#1a212b; --line:#2a3442; --fg:#d7dee8;
          --dim:#8b98a8; --acc:#4fa3ff; --ok:#38c172; --bad:#e3504f; }
  * { box-sizing:border-box; }
  body { margin:0; font:13px/1.45 ui-monospace,Consolas,monospace;
         background:var(--bg); color:var(--fg); }
  header { display:flex; align-items:center; gap:16px; padding:10px 16px;
           border-bottom:1px solid var(--line); }
  header h1 { font-size:15px; margin:0; color:var(--acc); }
  header .meta { color:var(--dim); }
  nav { display:flex; gap:2px; padding:6px 12px; border-bottom:1px solid var(--line);
        flex-wrap:wrap; }
  nav button { background:none; border:1px solid transparent; color:var(--dim);
               padding:4px 10px; cursor:pointer; font:inherit; border-radius:4px; }
  nav button.active { color:var(--fg); border-color:var(--line);
                      background:var(--panel); }
  main { padding:12px 16px; }
  table { border-collapse:collapse; width:100%; margin:8px 0 20px; }
  th, td { text-align:left; padding:4px 10px; border-bottom:1px solid var(--line);
           white-space:nowrap; overflow:hidden; text-overflow:ellipsis;
           max-width:420px; }
  th { color:var(--dim); font-weight:normal; position:sticky; top:0;
       background:var(--bg); }
  .ok { color:var(--ok); } .bad { color:var(--bad); }
  .bar { display:inline-block; height:9px; background:var(--acc);
         border-radius:2px; vertical-align:middle; }
  .barbg { display:inline-block; width:120px; height:9px; background:var(--panel);
           border-radius:2px; vertical-align:middle; margin-right:6px; }
  pre { background:var(--panel); padding:10px; border-radius:4px;
        overflow:auto; max-height:70vh; }
  h2 { font-size:13px; color:var(--dim); text-transform:uppercase;
       letter-spacing:.08em; margin:14px 0 2px; }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1>
  <span class="meta" id="updated"></span>
  <span class="meta bad" id="err"></span>
</header>
<nav id="nav"></nav>
<main id="main">loading…</main>
<script>
const TABS = ["overview","incidents","node_stats","metrics","tasks","actors",
              "launch","decisions","objects","memory","network",
              "placement_groups","serve","jobs","train","logs","events",
              "event_stats","traces","latency","stacks","profile"];
// hash may carry a selection suffix, e.g. "#traces:<trace_id>"
let tab = (location.hash.slice(1) || "overview").split(":")[0] || "overview";
window.addEventListener("hashchange", () => {
  tab = (location.hash.slice(1) || "overview").split(":")[0] || "overview";
  nav();
});
const $ = (id) => document.getElementById(id);

function nav() {
  $("nav").innerHTML = TABS.map(t =>
    `<button class="${t===tab?'active':''}" onclick="go('${t}')">${t}</button>`
  ).join("");
}
function go(t) { tab = t; location.hash = t; nav(); refresh(); }

async function j(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(path + " -> " + r.status);
  return r.json();
}
function esc(s) { return String(s).replace(/&/g,"&amp;").replace(/</g,"&lt;"); }
function table(rows, cols) {
  if (!rows || !rows.length) return "<p class='meta'>none</p>";
  cols = cols || Object.keys(rows[0]);
  return "<table><tr>" + cols.map(c=>`<th>${c}</th>`).join("") + "</tr>" +
    rows.map(r => "<tr>" + cols.map(c => {
      let v = r[c];
      if (v !== null && typeof v === "object") v = JSON.stringify(v);
      let cls = "";
      if (c === "state" || c === "status" || c === "alive")
        cls = /ALIVE|FINISHED|RUNNING|true|SUCCEEDED|HEALTHY|^INFO$/i.test(String(v)) ? "ok"
            : /DEAD|FAILED|false|UNHEALTHY|ERROR/i.test(String(v)) ? "bad" : "";
      return `<td class="${cls}">${esc(v===undefined?"":v)}</td>`;
    }).join("") + "</tr>").join("") + "</table>";
}
function bars(total, avail) {
  return "<table>" + Object.keys(total).sort().map(k => {
    const used = total[k] - (avail[k] || 0);
    const pct = total[k] ? Math.round(100*used/total[k]) : 0;
    return `<tr><td>${esc(k)}</td>
      <td><span class="barbg"><span class="bar" style="width:${Math.round(pct*1.2)}px"></span></span>
      ${used.toFixed(1)} / ${total[k].toFixed(1)} used</td></tr>`;
  }).join("") + "</table>";
}

const RENDER = {
  async overview() {
    const s = await j("/api/cluster_status");
    const nodes = s.nodes || [];
    return "<h2>resources</h2>" + bars(s.total || {}, s.available || {}) +
      `<h2>nodes (${nodes.length})</h2>` +
      table(nodes, ["node_id","alive","total","available","labels"]);
  },
  async tasks() {
    const rows = await j("/api/tasks");
    const by = {};
    rows.forEach(r => { by[r.state] = (by[r.state]||0)+1; });
    return "<h2>by state</h2><p>" +
      Object.entries(by).map(([k,v])=>`${k}: ${v}`).join(" · ") + "</p>" +
      "<h2>latest</h2>" + table(rows.slice(-200).reverse());
  },
  async actors() { return table(await j("/api/actors")); },
  async launch() {
    // control plane: actor-launch lifecycle profile — per-stage
    // latency stats over recent creations + the recent-launch ring
    const p = await j("/api/launch?limit=30");
    const head = `<p>${p.launched_total||0} launches total · ` +
      `${p.window||0} in window` +
      (p.total && p.total.count ?
        ` · total mean ${p.total.mean_ms}ms p95 ${p.total.p95_ms}ms` : "") +
      `</p>`;
    const stages = table(Object.entries(p.stages||{}).map(([k,v]) => ({
      stage: k.replace("_ms",""), count: v.count, "mean ms": v.mean_ms,
      "p50 ms": v.p50_ms, "p95 ms": v.p95_ms, "max ms": v.max_ms,
    })));
    const boot = Object.entries(p.worker_boot_stage_seconds||{})
      .map(([k,v])=>`${k.replace("_ms","")}=${v}s`).join(" · ");
    const recent = table((p.recent||[]).slice().reverse().map(r => ({
      actor: (r.actor||"").slice(0,14), name: r.name||"",
      node: (r.node||"").slice(0,8),
      stages: Object.entries(r.stages||{})
        .filter(([k])=>k!=="total_ms")
        .map(([k,v])=>`${k.replace("_ms","")}=${v}`).join(" "),
      "total ms": (r.stages||{}).total_ms,
      trace: r.trace || "",
    })));
    return head + "<h2>stage latency</h2>" + stages +
      (boot ? `<h2>worker boot (cumulative s)</h2><p>${boot}</p>` : "") +
      "<h2>recent launches</h2>" + recent;
  },
  async decisions() {
    // decision flight recorder: placement + autoscaler rows, newest first
    const rows = await j("/api/decisions?limit=200");
    const by = {};
    rows.forEach(r => { by[r.kind] = (by[r.kind]||0)+1; });
    const shaped = rows.slice().reverse().map(r => ({
      seq: r.seq, kind: r.kind,
      detail: Object.entries(r).filter(([k]) =>
        !["seq","t","kind"].includes(k))
        .map(([k,v]) => `${k}=${v!==null&&typeof v==="object"?JSON.stringify(v):v}`)
        .join(" "),
    }));
    return "<h2>by kind</h2><p>" +
      Object.entries(by).map(([k,v])=>`${k}: ${v}`).join(" · ") + "</p>" +
      "<h2>decisions (newest first)</h2>" +
      table(shaped, ["seq","kind","detail"]);
  },
  async objects() {
    const rows = await j("/api/objects");
    const total = rows.reduce((a,r)=>a+(r.size_bytes||0), 0);
    return `<p>${rows.length} objects, ${(total/1e6).toFixed(1)} MB</p>` +
      table(rows.slice(0,300));
  },
  async memory() {
    // memory plane: live objects grouped by creation callsite, store
    // usage split (sealed vs unsealed vs capacity), leak suspects
    const s = await j("/api/memory?group_by=callsite&limit=50");
    const st = s.store || {};
    const mb = (n)=> ((n||0)/1e6).toFixed(1);
    const rows = (s.rows||[]).map(g => ({
      callsite: g.group, count: g.count, mb: mb(g.bytes),
      leak: g.leak_suspect ? "YES" : "",
      classes: Object.entries(g.classes||{}).map(([c,n])=>`${c}:${n}`).join(" "),
      jobs: (g.jobs||[]).join(" "),
      exemplars: (g.exemplars||[]).map(o=>o.slice(0,12)).join(" "),
    }));
    const leaks = Object.values(s.leak_suspects||{}).map(i => ({
      callsite: i.callsite, live: i.live_count, mb: mb(i.live_bytes),
      growth_mb: mb(i.growth_bytes), window_s: i.window_s,
    }));
    return `<p>${s.total_objects} live objects, ${mb(s.total_bytes)} MB — ` +
      `store sealed ${mb(st.sealed_bytes)} / unsealed ${mb(st.unsealed_bytes)} ` +
      `/ capacity ${mb(st.capacity_bytes)} / high-water ${mb(st.highwater_bytes)} MB</p>` +
      (leaks.length ? `<h2>leak suspects</h2>` +
        table(leaks, ["callsite","live","mb","growth_mb","window_s"]) : "") +
      `<h2>by creation callsite</h2>` +
      table(rows, ["callsite","count","mb","leak","classes","jobs","exemplars"]);
  },
  async network() {
    // transfer plane: per-link ledger matrix, relay topology (recent
    // transfers grouped by object, hop-indented), fleet path summary
    const s = await j("/api/net");
    const mb = (n)=> ((n||0)/1e6).toFixed(1);
    const sum = s.summary || {};
    const head = `<p>${sum.inflight||0} in flight · ` +
      `${sum.retries||0} retries · ${sum.stalled||0} stalls · ` +
      `${sum.leaked_buffers||0} leaked buffers (${mb(sum.leaked_bytes)} MB) · ` +
      `${sum.slow_link_events||0} slow-link events</p>`;
    const paths = table((sum.rows||[]).map(r => ({
      path: r.group, mb: mb(r.bytes), transfers: r.transfers,
      "GiB/s": r.gib_per_s == null ? "" : r.gib_per_s,
      failures: r.failures, stalls: r.stalls,
    })));
    const links = table((s.links||[]).map(r => ({
      state: r.slow ? "SLOW" : "ok", src: r.src, dst: r.dst, path: r.path,
      mb: mb(r.bytes), xfers: r.transfers, fail: r.failures,
      stall: r.stalls, infl: r.inflight,
      "GiB/s": r.ewma_gib_per_s == null ? "" : r.ewma_gib_per_s,
      hop: r.max_hop,
    })), ["state","src","dst","path","mb","xfers","fail","stall","infl",
          "GiB/s","hop"]);
    // relay topology: recent transfers of one object rendered as a tree
    // of hops (hop 0 = pull off the sealed origin)
    const byObj = {};
    (s.transfers||[]).forEach(t => {
      (byObj[t.object_id] = byObj[t.object_id] || []).push(t);
    });
    const relays = Object.entries(byObj)
      .filter(([,ts]) => ts.length > 1 || ts.some(t => t.hop > 0))
      .slice(0, 8).map(([oid, ts]) =>
        `<h2>object ${esc(oid.slice(0,16))} — relay tree</h2>` +
        ts.sort((a,b)=>(a.hop-b.hop)).map(t =>
          `<div style="margin-left:${(t.hop||0)*18}px">` +
          `hop ${t.hop||0}: ${esc(t.src)} → ${esc(t.dst)} ` +
          `<span class="meta">${t.path} ${mb(t.bytes)} MB` +
          `${t.gib_per_s != null ? " @ " + t.gib_per_s + " GiB/s" : ""}` +
          `${t.ok ? "" : " FAILED"}</span></div>`).join("")
      ).join("");
    const recent = table((s.transfers||[]).slice(0, 30).map(t => ({
      state: t.ok ? "ok" : "FAILED", object: t.object_id.slice(0,14),
      link: `${t.src}→${t.dst}`, path: t.path, hop: t.hop,
      mb: mb(t.bytes), "GiB/s": t.gib_per_s == null ? "" : t.gib_per_s,
      stages: Object.entries(t.stages_ms||{})
        .map(([k,v])=>`${k.replace("_ms","")}=${v}`).join(" "),
      trace: t.trace_id || "",
    })), ["state","object","link","path","hop","mb","GiB/s","stages","trace"]);
    return head + "<h2>by path</h2>" + paths +
      "<h2>link matrix</h2>" + links + relays +
      "<h2>recent transfers</h2>" + recent;
  },
  async placement_groups() { return table(await j("/api/placement_groups")); },
  async serve() {
    const s = await j("/api/serve");
    return "<pre>" + esc(JSON.stringify(s, null, 2)) + "</pre>";
  },
  async jobs() {
    // multi-tenant job plane: arbitration rows (priority / quota / live
    // usage / admission + queue position) over every job the scheduler
    // has seen, then the JobSubmissionClient's submission records
    const s = await j("/api/jobs");
    const jobs = (s.jobs || []).map(r => ({
      name: r.name, status: r.admission,
      "q#": r.queue_position || "",
      prio: r.priority, weight: r.weight,
      running: r.running, ready: r.ready,
      usage: r.usage, quota: r.quota,
      "obj MB": ((r.object_store_bytes||0)/1e6).toFixed(1),
      preempt: r.preemptions, oom: r.oom_kills,
    }));
    const subs = s.submissions || [];
    return `<h2>arbitration (${jobs.length})</h2>` +
      table(jobs, ["name","status","q#","prio","weight","running","ready",
                   "usage","quota","obj MB","preempt","oom"]) +
      `<h2>submissions (${subs.length})</h2>` + table(subs);
  },
  async train() {
    // training step plane: run digests; ?run drills into the per-rank
    // step waterfall (stage-colored bars) + downtime ledger
    const STAGES = ["data_wait_ms","host_to_device_ms","compile_ms",
                    "compute_ms","collective_wait_ms","checkpoint_stall_ms",
                    "other_ms"];
    const COLORS = {data_wait_ms:"#e3a04f", host_to_device_ms:"#b06fd8",
                    compile_ms:"#e3504f", compute_ms:"#38c172",
                    collective_wait_ms:"#4fa3ff",
                    checkpoint_stall_ms:"#d8c94f", other_ms:"#6b7a8c"};
    const sel = location.hash.split(":")[1];
    if (sel) {
      const d = await j("/api/train?run=" + sel);
      if (!d.run) return `<p>no step records for run ${esc(sel)}</p>`;
      const meta = d.meta || {}, gp = meta.goodput || {};
      const ledger = meta.downtime_ledger || [];
      const legend = STAGES.map(s =>
        `<span style="color:${COLORS[s]}">■ ${s.replace("_ms","")}</span>`
      ).join(" ");
      const bar = (st, wall) => {
        if (!wall) return "";
        return `<span class="barbg" style="width:240px">` + STAGES.map(k => {
          const w = Math.round(240 * (st[k]||0) / wall);
          return w ? `<span class="bar" style="width:${w}px;background:${COLORS[k]}"></span>` : "";
        }).join("") + `</span>`;
      };
      const rows = [];
      (d.steps || []).slice(-50).forEach(s => {
        const skew = (d.skew || {})[s.step] || {};
        Object.keys(s.ranks || {}).sort().forEach(r => {
          const rec = s.ranks[r], st = rec.stages || {};
          rows.push(`<tr><td>${s.step}</td><td>${r}` +
            `${skew.straggler_rank == r && skew.skew_ms > 0 ? " ⚠" : ""}</td>` +
            `<td>${bar(st, rec.wall_ms)}</td>` +
            `<td>${(rec.wall_ms||0).toFixed(1)}ms</td>` +
            `<td>${rec.recompiled ? "<span class='bad'>RECOMPILED</span>" : ""}` +
            `${rec.trace_id ? ` <a href="#traces:${rec.trace_id}">trace</a>` : ""}</td></tr>`);
        });
      });
      return `<h2>run ${esc(d.run)} — world ${d.world}, ` +
        `${d.steps_seen} steps, ${d.recompiles} recompiles` +
        `${gp.goodput != null ? `, goodput ${gp.goodput.toFixed(3)}` : ""}</h2>` +
        `<p>${legend}</p>` +
        (ledger.length ? "<h2>downtime ledger</h2>" +
          table(ledger.map(e => ({cause: e.cause,
            seconds: (e.seconds||0).toFixed(2), detail: e.detail||""}))) : "") +
        "<h2>step waterfall (per rank)</h2>" +
        "<table><tr><th>step</th><th>rank</th><th>stages</th><th>wall</th>" +
        "<th></th></tr>" + rows.join("") + "</table>";
    }
    const rows = await j("/api/train");
    if (!rows.length) return "<p>no training runs recorded</p>";
    const cols = ["run","world","steps","recompiles","goodput","downtime s",
                  "data wait","skew ms","status"];
    return "<h2>training runs (click to inspect)</h2>" +
      "<table><tr>" + cols.map(c=>`<th>${c}</th>`).join("") + "</tr>" +
      rows.map(r =>
        `<tr><td><a href="#train:${encodeURIComponent(r.run)}" ` +
        `onclick="setTimeout(refresh,0)">${esc(r.run)}</a></td>` +
        `<td>${r.world}</td><td>${r.steps}</td><td>${r.recompiles}</td>` +
        `<td>${r.goodput == null ? "" : r.goodput.toFixed(3)}</td>` +
        `<td>${(r.downtime_s||0).toFixed(1)}</td>` +
        `<td>${r.data_wait_ratio == null ? "" :
               (100*r.data_wait_ratio).toFixed(1) + "%"}</td>` +
        `<td>${(r.max_skew_ms||0).toFixed(1)}</td>` +
        `<td class="${/finished/.test(r.status)?'ok':/failed/.test(r.status)?'bad':''}">${esc(r.status)}</td></tr>`
      ).join("") + "</table>";
  },
  async logs() { return table(await j("/api/logs")); },
  async incidents() {
    // alerting plane: open/closed incidents + registered SLO burn status;
    // "#incidents:<id>" drills into one record's cross-plane digest
    const sel = (location.hash.slice(1).split(":")[1] || "");
    if (sel) {
      const inc = await j("/api/incidents?id=" + encodeURIComponent(sel));
      if (!inc) return "<p class='meta'>no such incident</p>";
      const d = inc.digest || {};
      let html = `<h2>${esc(inc.id)} [${esc(inc.kind)}] ` +
        `${esc(inc.subject)}</h2>` +
        `<p>state=${esc(inc.state)} severity=${esc(inc.severity)} ` +
        `triggers=${inc.count}` +
        (inc.duration_s != null ? ` duration=${inc.duration_s}s` : "") +
        `</p>` +
        (inc.verdict ? `<p><b>verdict:</b> ${esc(inc.verdict)}</p>` : "") +
        `<p>planes joined: ${esc((d.planes||[]).join(", "))}</p>`;
      if (d.traces && d.traces.length)
        html += "<h2>exemplar traces</h2>" + table(d.traces);
      if (d.net && d.net.links && d.net.links.length)
        html += "<h2>link ledger</h2>" + table(d.net.links,
          ["src","dst","path","ewma_gib_per_s","stalls","failures","slow"]);
      if (d.memory && d.memory.top_callsites)
        html += "<h2>memory top callsites</h2>" +
          table(d.memory.top_callsites);
      if (d.train) html += "<h2>train run</h2>" + table([d.train]);
      if (d.control && d.control.launches)
        html += "<h2>recent launches</h2>" + table(d.control.launches);
      if (d.events && d.events.length)
        html += "<h2>correlated events</h2>" +
          table(d.events.slice(-30).reverse(),
                ["time","severity","type","source","message"]);
      return html + `<p><a href="#incidents" onclick="go('incidents')">` +
        `back to incident list</a></p>`;
    }
    const body = await j("/api/incidents?limit=100");
    const incRows = (body.incidents || []).map(r => ({
      id: `<a href="#incidents:${esc(r.id)}" ` +
          `onclick="location.hash='incidents:${esc(r.id)}';refresh()">` +
          `${esc(r.id)}</a>`,
      state: r.state, kind: r.kind, subject: r.subject,
      triggers: r.count,
      duration_s: r.duration_s != null ? r.duration_s : "open",
      planes: (r.planes || []).join(","),
      verdict: r.verdict || "",
    }));
    const sloRows = (body.slos || []).map(s => ({
      name: s.name, kind: s.kind, target: s.target,
      state: s.ok ? "OK" : "BREACHED",
      subjects: s.subjects, breaches: s.breaches_total,
      worst: s.worst ? JSON.stringify(s.worst) : "",
    }));
    // id cells carry markup: render with a raw table to keep the links
    const raw = (rows, cols) => !rows.length ? "<p class='meta'>none</p>" :
      "<table><tr>" + cols.map(c=>`<th>${c}</th>`).join("") + "</tr>" +
      rows.map(r => "<tr>" + cols.map(c => {
        const cls = (c === "state")
          ? (/open|BREACHED/.test(String(r[c])) ? "bad" : "ok") : "";
        return `<td class="${cls}">${c==="id" ? r[c] : esc(r[c]??"")}</td>`;
      }).join("") + "</tr>").join("") + "</table>";
    return `<h2>incidents (${incRows.length})</h2>` +
      raw(incRows, ["id","state","kind","subject","triggers","duration_s",
                    "planes","verdict"]) +
      `<h2>SLOs (${sloRows.length})</h2>` +
      raw(sloRows, ["name","state","kind","target","subjects","breaches",
                    "worst"]);
  },
  async events() {
    // cluster event log (failure forensics): newest first, severity colored
    const rows = await j("/api/events?limit=500");
    const by = {};
    rows.forEach(r => { by[r.severity] = (by[r.severity]||0)+1; });
    const cols = ["event_id","state","type","source","message","task_id",
                  "node_id","pid"];
    const shaped = rows.slice().reverse().map(r => {
      const o = {};
      cols.forEach(c => { o[c] = r[c]; });
      o.state = r.severity;  // severity under the colorized "state" column
      return o;
    });
    return "<h2>by severity</h2><p>" +
      Object.entries(by).map(([k,v])=>`${k}: ${v}`).join(" · ") + "</p>" +
      "<h2>latest</h2>" + table(shaped, cols);
  },
  async event_stats() {
    const s = await j("/api/event_stats");
    return "<pre>" + esc(JSON.stringify(s, null, 2)) + "</pre>";
  },
  async metrics() {
    // runtime-internal series (telemetry plane); /metrics has the same
    // data in Prometheus text for scrapers
    const series = await j("/api/runtime_metrics");
    return series.map(s => {
      const rows = Object.entries(s.data || {}).map(([labels, v]) =>
        ({labels, value: v}));
      return `<h2>${esc(s.name)} <span class="meta">(${esc(s.kind)})</span></h2>` +
        `<p class="meta">${esc(s.description || "")}</p>` + table(rows);
    }).join("");
  },
  async stacks() {
    const s = await j("/api/stacks");
    return Object.entries(s).map(([proc, txt]) =>
      `<h2>${esc(proc)}</h2><pre>${esc(txt)}</pre>`).join("");
  },
  async traces() {
    // request-tracing plane: recent traces; ?id= drills into one span tree
    const sel = location.hash.split(":")[1];
    if (sel) {
      const t = await j("/api/trace?id=" + sel);
      const render = (s, depth) => {
        const bd = Object.entries(s.breakdown||{})
          .map(([k,v]) => `${k.replace("_ms","")}=${v}ms`).join(" ");
        return `<div style="margin-left:${depth*18}px">` +
          `<b>${esc(s.name||s.span_id.slice(0,8))}</b> ` +
          `${(s.duration_ms||0).toFixed(1)}ms ` +
          `<span class="meta">${esc(bd)}</span></div>` +
          (s.children||[]).map(c => render(c, depth+1)).join("");
      };
      return `<h2>trace ${esc(t.trace_id)} — ` +
        `${(t.duration_ms||0).toFixed(1)}ms, ${t.spans} spans</h2>` +
        (t.tree||[]).map(r => render(r, 0)).join("") +
        "<h2>critical path</h2>" +
        table((t.critical_path||[]).map(r => ({
          name: r.name, "ms": (r.duration_ms||0).toFixed(1),
          breakdown: Object.entries(r.breakdown||{})
            .map(([k,v]) => `${k.replace("_ms","")}=${v}`).join(" "),
        })));
    }
    const rows = await j("/api/traces?limit=100");
    if (!rows.length) return "<p>no traces recorded yet</p>";
    return "<h2>recent traces (click to inspect)</h2>" +
      rows.map(r =>
        `<div><a href="#traces:${r.trace_id}" onclick="setTimeout(refresh,0)">` +
        `${r.trace_id}</a> <b>${esc(r.root||"")}</b> ` +
        `<span class="meta">${r.events} events, ` +
        `${r.last_time ? ((Date.now()/1000)-r.last_time).toFixed(1) : "?"}s ago` +
        `</span></div>`).join("");
  },
  async latency() {
    // sliding-window p50/p95/p99 per job with exemplar trace links
    const s = await j("/api/job_latency");
    return Object.entries(s).map(([job, w]) =>
      `<h2>job ${esc(job)} <span class="meta">(${w.count} in window)</span></h2>` +
      table([{p50: w.p50, p95: w.p95, p99: w.p99, max: w.max}]) +
      (w.exemplars||[]).map(e =>
        `<p class="meta">slow: ${e.latency_ms}ms — ` +
        `<a href="#traces:${e.trace_id}" onclick="tab='traces';nav();setTimeout(refresh,0)">${e.trace_id}</a></p>`
      ).join("")
    ).join("") || "<p>no samples in window</p>";
  },
  async node_stats() {
    // per-node reporter metrics (cpu/mem/object-store), heartbeat-pushed
    const s = await j("/api/node_stats");
    const rows = Object.entries(s).map(([nid, st]) => ({
      node: st.node || nid.slice(0,12),
      "cpu %": st.cpu_percent,
      "rss MB": st.rss_bytes ? (st.rss_bytes/1e6).toFixed(1) : "",
      "store MB": st.object_store_bytes ? (st.object_store_bytes/1e6).toFixed(1) : "0.0",
      "mem avail GB": st.mem_available ? (st.mem_available/1e9).toFixed(2) : "",
      workers: st.workers,
      "lease q/run": (st.lease_queued??"") + "/" + (st.lease_running??""),
      "hb age s": st.heartbeat_age_s ?? 0,
    }));
    return table(rows);
  },
  async profile() {
    // py-spy-style sampled stacks across node daemons (2s capture)
    $("main").innerHTML = "sampling node stacks for 2s\u2026";
    const s = await j("/api/profile?duration=2");
    return Object.entries(s).map(([node, counts]) => {
      const total = Object.values(counts).reduce((a,b)=>a+b, 0) || 1;
      const lines = Object.entries(counts).slice(0, 40).map(([stack, n]) =>
        `${String(Math.round(100*n/total)).padStart(3)}%  ${esc(stack)}`);
      return `<h2>${esc(node)}</h2><pre>${lines.join("\n")}</pre>`;
    }).join("");
  },
};

let timer = null;
async function refresh() {
  try {
    $("main").innerHTML = await RENDER[tab]();
    $("updated").textContent = "updated " + new Date().toLocaleTimeString();
    $("err").textContent = "";
  } catch (e) {
    $("err").textContent = String(e);
  }
  clearTimeout(timer);
  timer = setTimeout(refresh, (tab === "stacks" || tab === "profile") ? 15000 : 2000);
}
nav();
refresh();
</script>
</body>
</html>
"""
