"""ray_tpu: a TPU-native distributed AI runtime.

Public API parity with the reference's L7 surface (``python/ray/_private/
worker.py:1225,2576,2691,2756``; ``python/ray/remote_function.py:266``;
``python/ray/actor.py:566``): ``init/shutdown``, ``@remote``, ``get/put/wait``,
actors, named actors, placement groups, and the library stack (``data``,
``train``, ``tune``, ``serve``, ``rl``) as pure clients of this core.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

from ray_tpu import exceptions
from ray_tpu._private import worker as _worker
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.worker import (
    ObjectRef,
    ObjectRefGenerator,
    get_runtime,
    is_initialized,
)
from ray_tpu.actor import ActorClass, ActorHandle, get_actor, kill
from ray_tpu.remote_function import RemoteFunction
from ray_tpu.runtime_context import get_runtime_context

__version__ = "0.1.0"

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "get_runtime_context",
    "ObjectRef",
    "ObjectRefGenerator",
    "ActorClass",
    "ActorHandle",
    "exceptions",
    "method",
    "nodes",
    "cluster_resources",
    "available_resources",
    "timeline",
    "trace",
    "recent_traces",
    "request_profile",
    "profile_dump",
    "job_scope",
    "__version__",
]


def init(**kwargs):
    """Start (or connect to) the runtime. Parity: ``ray.init``."""
    return _worker.init(**kwargs)


def shutdown():
    _worker.shutdown()


def remote(*args, **options):
    """Decorator turning a function into a remote task / class into an actor."""

    def decorate(obj):
        import inspect

        if inspect.isclass(obj):
            return ActorClass(obj, options)
        return RemoteFunction(obj, options)

    if len(args) == 1 and not options and (callable(args[0])):
        return decorate(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_cpus=2)")
    return decorate


def method(num_returns: int = 1):
    """Decorator recording per-method defaults (parity: ``ray.method``)."""

    def decorate(m):
        m.__ray_num_returns__ = num_returns
        return m

    return decorate


def job_scope(
    *,
    name: str = "",
    priority: int = 0,
    weight: float = 1.0,
    quota=None,
    meta=None,
):
    """Run a block of submissions as a distinct tenant of the multi-tenant
    job plane: tasks, actors, and puts created inside the ``with`` block
    are arbitrated (weighted-fair queueing), quota-capped, and
    priority-ranked under one job. ``quota`` caps live usage per resource
    (plus the ``object_store_bytes`` pseudo-resource); ``priority`` feeds
    preemption and admission ordering. Raises
    ``exceptions.JobAdmissionError`` if admission control rejects the
    submission outright."""
    return get_runtime().job_scope(
        name=name, priority=priority, weight=weight, quota=quota, meta=meta
    )


def put(value: Any) -> ObjectRef:
    rt = get_runtime()
    return ObjectRef(rt.put(value), _owned=True)


def get(
    refs: Union[ObjectRef, Sequence[ObjectRef]],
    *,
    timeout: Optional[float] = None,
) -> Any:
    rt = get_runtime()
    if isinstance(refs, ObjectRef):
        return rt.get_objects([refs.id()], timeout=timeout)[0]
    from ray_tpu.dag import CompiledDAGRef

    if isinstance(refs, CompiledDAGRef):
        # parity: ray.get accepts compiled-DAG result refs
        return refs.get(timeout)
    if isinstance(refs, (list, tuple)):
        if not refs:
            return []
        if all(isinstance(r, CompiledDAGRef) for r in refs):
            return [r.get(timeout) for r in refs]
        if not all(isinstance(r, ObjectRef) for r in refs):
            raise TypeError("get() accepts an ObjectRef or a list of ObjectRefs")
        return rt.get_objects([r.id() for r in refs], timeout=timeout)
    raise TypeError(f"get() got {type(refs)}")


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
) -> tuple:
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    rt = get_runtime()
    id_to_ref = {r.id(): r for r in refs}
    ready_ids, not_ready_ids = rt.wait(
        [r.id() for r in refs], num_returns=num_returns, timeout=timeout
    )
    return [id_to_ref[i] for i in ready_ids], [id_to_ref[i] for i in not_ready_ids]


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True) -> None:
    rt = get_runtime()
    task_id = ref.id().task_id()
    if hasattr(rt, "scheduler"):
        rt.scheduler.post(("cancel", task_id, force))
    else:
        rt._send(("cmd", ("cancel", task_id, force)))


def nodes() -> List[dict]:
    """Parity: ``ray.nodes()``."""
    rt = get_runtime()
    if hasattr(rt, "scheduler"):
        return rt.scheduler_rpc("list_nodes", ())
    return rt.rpc("list_nodes")


def cluster_resources() -> dict:
    total: dict = {}
    for n in nodes():
        if n["alive"]:
            for k, v in n["total"].items():
                total[k] = total.get(k, 0.0) + v
    return total


def available_resources() -> dict:
    total: dict = {}
    for n in nodes():
        if n["alive"]:
            for k, v in n["available"].items():
                total[k] = total.get(k, 0.0) + v
    return total


def timeline(filename: Optional[str] = None) -> List[dict]:
    """Chrome-trace task events. Parity: ``ray.timeline(filename=...)``
    (``python/ray/_private/state.py:944``).

    Forces a cluster-wide telemetry flush first (read-your-writes despite
    the batched pipeline), then renders the merged event log as a
    chrome://tracing array: per-task lifecycle phase spans
    (SUBMITTED/QUEUED/DISPATCHED/RUNNING/FINISHED‑or‑FAILED), profile
    spans with trace-context parent links (one tree across processes),
    and stable per-task tids. With ``filename`` the JSON array is also
    written to disk, ready to load into chrome://tracing or Perfetto.
    """
    rt = get_runtime()
    if not hasattr(rt, "scheduler"):
        raise RuntimeError("timeline() is driver-only")
    from ray_tpu._private import telemetry as _telemetry

    _telemetry.flush()
    rt.scheduler.request_telemetry_flush()
    # read via the loop-serialized rpc: the loop appends telemetry batches
    # concurrently, and list(deque) from this thread could see a mutation
    events = rt.scheduler_rpc("task_events", ())
    trace = _telemetry.build_chrome_trace(events)
    if filename:
        import json as _json

        with open(filename, "w") as fh:
            _json.dump(trace, fh)
    return trace


def _sched_rpc(op: str, *args):
    """One scheduler rpc, in-process driver or remote-attached alike (the
    single place the runtime-dispatch fallback lives)."""
    rt = get_runtime()
    if hasattr(rt, "scheduler_rpc"):
        return rt.scheduler_rpc(op, args)
    return rt.rpc(op, *args)


def _traced_rpc(op: str, *args):
    """Flush telemetry cluster-wide (read-your-writes), then run a
    scheduler rpc."""
    rt = get_runtime()
    from ray_tpu._private import telemetry as _telemetry

    _telemetry.flush()
    scheduler = getattr(rt, "scheduler", None)
    if scheduler is not None:
        scheduler.request_telemetry_flush()
    return _sched_rpc(op, *args)


def trace(trace_id: str):
    """Reconstruct one request's cross-process span tree and critical-path
    latency decomposition (submit -> queue_wait -> dispatch -> arg_fetch ->
    execute -> result_put -> stream_yield; serve spans included).

    ``trace_id`` comes from :func:`recent_traces`, the
    ``x-raytpu-trace-id`` serve response header,
    ``ray_tpu.util.tracing.current_trace_id()``, or a latency exemplar.
    Returns a :class:`ray_tpu._private.trace.Trace`; print
    ``.summary()`` or inspect ``.to_dict()``.
    """
    from ray_tpu._private.trace import build_trace

    trace_id = str(trace_id)
    events = _traced_rpc("trace_events", trace_id)
    return build_trace(events, trace_id)


def recent_traces(limit: int = 100) -> List[dict]:
    """Digests of recently-seen traces, newest first: ``{trace_id,
    first_time, last_time, root, events}``. Reads the scheduler's index
    directly — no cluster-wide flush fan-out (the dashboard polls this
    every couple of seconds; only per-trace event reads need
    read-your-writes)."""
    from ray_tpu._private import telemetry as _telemetry

    _telemetry.flush()  # local buffer only: direct-call submission anchors
    return _sched_rpc("list_traces", int(limit))


def train_timeline(run: str, max_steps: Optional[int] = None):
    """One training run's step-time attribution — "where did the step go".

    Returns a :class:`ray_tpu._private.stepplane.TrainTimeline`: per-rank
    step records decomposed into data_wait -> host_to_device -> compile ->
    compute -> collective_wait (with the straggler rank) ->
    checkpoint_stall -> other, run-level stage shares, per-operator ingest
    stalls, recompile flags, and the goodput downtime ledger attributed by
    cause. Print ``.summary()`` for the per-rank step waterfall or inspect
    ``.to_dict()``. ``run`` is the RunConfig name (see
    ``state.list_train_runs()``)."""
    from ray_tpu._private.stepplane import TrainTimeline

    data = _traced_rpc("train_run", str(run), max_steps)
    return TrainTimeline(data or {})


def request_profile(hz: float = 99.0, duration_s: float = 10.0) -> int:
    """Boost the continuous sampling profiler cluster-wide for a bounded
    window (on top of the steady-state ``profiler_hz``). Returns the number
    of workers reached; the calling process is boosted too."""
    from ray_tpu._private import sampler as _sampler

    _sampler.boost(hz, duration_s)
    return _sched_rpc("request_profile", hz, duration_s)


def profile_dump(
    filename: str,
    format: str = "speedscope",
    task_id: Optional[str] = None,
    trace_id: Optional[str] = None,
) -> int:
    """Export the cluster's aggregated continuous-profiler samples as a
    flame graph: ``format="speedscope"`` (JSON for speedscope.app, one
    profile per task) or ``"collapsed"`` (Brendan-Gregg collapsed stacks).
    Optional ``task_id``/``trace_id`` narrow attribution to one task or one
    request. Returns profiles/lines written."""
    from ray_tpu._private import sampler as _sampler

    _sampler.get_sampler().drain()
    rows = _traced_rpc("profile_samples", task_id, trace_id)
    if format == "collapsed":
        return _sampler.write_collapsed(rows, filename)
    if format == "speedscope":
        return _sampler.write_speedscope(rows, filename)
    raise ValueError(f"unknown flame-graph format {format!r}")


def __getattr__(name):
    # lazy subpackage access: `import ray_tpu; ray_tpu.data.range(...)` works
    # without eagerly importing the libraries (parity: `ray.data` et al)
    if name in ("data", "train", "tune", "serve", "rl", "workflow", "util", "autoscaler"):
        import importlib

        return importlib.import_module(f"ray_tpu.{name}")
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
