"""Runtime context. Parity: ``python/ray/runtime_context.py``
(``ray.get_runtime_context()``)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ray_tpu._private import worker as _worker


@dataclass
class RuntimeContext:
    job_id: Optional[str]
    node_id: Optional[str]
    worker_id: Optional[str]
    actor_id: Optional[str]
    task_id: Optional[str]
    accelerator_ids: Optional[dict] = None

    def get_job_id(self):
        return self.job_id

    def get_node_id(self):
        return self.node_id

    def get_actor_id(self):
        return self.actor_id

    def get_task_id(self):
        return self.task_id

    def get_worker_id(self):
        return self.worker_id

    def get_accelerator_ids(self) -> dict:
        """Device instances assigned to the current task (parity:
        ``RuntimeContext.get_accelerator_ids``): ``{"TPU": ["0", "1"]}``.
        Empty lists when the task requested no indexed resources."""
        out = {"TPU": [], "GPU": []}
        for name, alloc in (self.accelerator_ids or {}).items():
            out[name] = [str(i) for i, _ in alloc]
        return out


def get_runtime_context() -> RuntimeContext:
    rt = _worker.get_runtime()
    if hasattr(rt, "scheduler"):  # driver
        return RuntimeContext(
            job_id=rt.job_id.hex(),
            node_id=rt.node.head_node_id.hex(),
            worker_id=None,
            actor_id=None,
            task_id=rt.task_id.hex(),
        )
    tid = rt.current_task_id
    actor = rt._actor_id
    return RuntimeContext(
        job_id=tid.job_id().hex() if tid else None,
        node_id=None,
        worker_id=rt.worker_id.hex(),
        actor_id=actor.hex() if actor else None,
        task_id=tid.hex() if tid else None,
        accelerator_ids=getattr(rt, "_accel_alloc", None),
    )
