"""Actor classes and handles.

Design parity: ``python/ray/actor.py`` — ``ActorClass`` (``:566``),
``ActorClass._remote`` (``:854``), ``ActorHandle`` + ``ActorMethod``; named
actors via the GCS name registry (``gcs_actor_manager.h:278``); handles pickle
into tasks and reconstruct on the borrower side.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu import exceptions as exc
from ray_tpu._private.ids import ActorID, ObjectID, TaskID
from ray_tpu._private.runtime_env import upload_runtime_env as _upload_runtime_env
from ray_tpu.util.tracing import for_submission as _trace_for_submission
from ray_tpu._private.task_spec import SchedulingStrategy, TaskSpec, TaskType
from ray_tpu._private.worker import ObjectRef, ObjectRefGenerator, get_runtime, pack_args
from ray_tpu.remote_function import resolve_resources, resolve_strategy

_DEFAULT_ACTOR_OPTIONS = dict(
    num_cpus=1.0,
    num_tpus=0.0,
    resources=None,
    max_restarts=0,
    max_task_retries=0,
    max_concurrency=1,
    name=None,
    namespace=None,
    lifetime=None,  # None | "detached"
    scheduling_strategy=None,
    runtime_env=None,
    memory=None,
)


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str, num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def __call__(self, *a, **kw):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly; "
            f"use '.remote()'."
        )

    def options(self, num_returns: int = 1, **_ignored):
        return ActorMethod(self._handle, self._method_name, num_returns)

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._method_name, args, kwargs, self._num_returns
        )

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)


class ActorHandle:
    def __init__(self, actor_id: ActorID, method_meta: Dict[str, int], owned: bool = False):
        self._actor_id = actor_id
        self._method_meta = method_meta
        self._owned = owned
        if owned:
            try:
                get_runtime().actor_handle_count(actor_id, 0)  # registration no-op
            except Exception:
                pass

    @property
    def _id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str):
        meta = object.__getattribute__(self, "_method_meta")
        if name in meta:
            return ActorMethod(self, name, meta[name])
        raise AttributeError(name)

    # method-name pickles are identical across calls: cache them (hot path —
    # one cloudpickle.dumps per actor call showed up in the core microbench)
    _method_blob_cache: dict = {}

    def _submit_method(self, method_name: str, args, kwargs, num_returns: int):
        rt = get_runtime()
        streaming = num_returns == "streaming"
        packed_args, packed_kwargs = pack_args(rt, args, kwargs)
        blob = self._method_blob_cache.get(method_name)
        if blob is None:
            blob = cloudpickle.dumps(method_name)
            self._method_blob_cache[method_name] = blob
        spec = TaskSpec(
            task_id=rt.new_task_id(),
            task_type=TaskType.ACTOR_TASK,
            function=blob,
            args=packed_args,
            kwargs=packed_kwargs,
            num_returns=1 if streaming else num_returns,
            resources={},
            name=f"{method_name}",
            actor_id=self._actor_id,
            is_streaming=streaming,
            trace_ctx=_trace_for_submission(),
        )
        rt.submit(spec)
        if streaming:
            return ObjectRefGenerator(
                spec.task_id, ObjectRef(ObjectID.for_return(spec.task_id, 0), _owned=True)
            )
        refs = [ObjectRef(oid, _owned=True) for oid in spec.return_ids()]
        return refs[0] if num_returns == 1 else refs

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_meta))

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]})"

    def __del__(self):
        if getattr(self, "_owned", False):
            try:
                get_runtime().actor_handle_count(self._actor_id, -1)
            except Exception:
                pass


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._name = cls.__name__
        self._options = dict(_DEFAULT_ACTOR_OPTIONS)
        self._options.update(options or {})
        # keys the user set explicitly: these become lifetime resources
        self._explicit = set((options or {}).keys())
        self._pickled: Optional[bytes] = None
        functools.update_wrapper(self, cls, updated=[])

    def __call__(self, *a, **kw):
        raise TypeError(
            f"Actor class '{self._name}' cannot be instantiated directly; "
            f"use '{self._name}.remote()'."
        )

    def options(self, **updates) -> "ActorClass":
        new = ActorClass(self._cls, {**self._options, **updates})
        new._explicit = self._explicit | set(updates.keys())
        new._pickled = self._pickled
        return new

    def _method_meta(self) -> Dict[str, int]:
        meta = {}
        for name in dir(self._cls):
            if name.startswith("__") and name not in ("__call__",):
                continue
            m = getattr(self._cls, name, None)
            if callable(m):
                meta[name] = getattr(m, "__ray_num_returns__", 1)
        meta["__ray_terminate__"] = 1
        return meta

    def remote(self, *args, **kwargs) -> ActorHandle:
        rt = get_runtime()
        opts = self._options
        if self._pickled is None:
            self._pickled = cloudpickle.dumps(self._cls)
        name = opts.get("name")
        namespace = opts.get("namespace") or "default"
        actor_id = ActorID.of(rt.new_task_id().job_id())
        if name:
            if not rt.rpc("claim_actor_name", namespace, name, actor_id):
                raise ValueError(f"actor name '{name}' already taken")
        packed_args, packed_kwargs = pack_args(rt, args, kwargs)
        spec = TaskSpec(
            task_id=rt.new_task_id(),
            task_type=TaskType.ACTOR_CREATION,
            function=self._pickled,
            args=packed_args,
            kwargs=packed_kwargs,
            num_returns=1,
            resources=resolve_resources(opts),
            lifetime_resources=resolve_resources(
                {k: v for k, v in opts.items() if k in self._explicit}
            ),
            name=f"{self._name}.__init__",
            actor_id=actor_id,
            max_restarts=int(opts.get("max_restarts") or 0),
            max_concurrency=int(opts.get("max_concurrency") or 1),
            max_task_retries=int(opts.get("max_task_retries") or 0),
            detached=opts.get("lifetime") == "detached",
            actor_name=name,
            namespace=namespace,
            scheduling_strategy=resolve_strategy(opts),
            runtime_env=_upload_runtime_env(rt, opts.get("runtime_env")),
            trace_ctx=_trace_for_submission(),
        )
        rt.submit(spec)
        return ActorHandle(actor_id, self._method_meta(), owned=True)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    rt = get_runtime()
    actor_id = rt.rpc("get_actor_by_name", namespace, name)
    if actor_id is None:
        raise ValueError(f"no actor named '{name}' in namespace '{namespace}'")
    # method metadata is not stored server-side; return a dynamic handle
    return _DynamicActorHandle(actor_id)


class _DynamicActorHandle(ActorHandle):
    """Handle from get_actor: resolves any attribute as a method."""

    def __init__(self, actor_id: ActorID):
        super().__init__(actor_id, {}, owned=False)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name, 1)

    def __reduce__(self):
        # the base reduce would rebuild a plain ActorHandle whose EMPTY
        # method table can't resolve any method — a dynamic handle must
        # stay dynamic across pickling (serve ships re-adopted replica
        # handles through the controller this way)
        return (_DynamicActorHandle, (self._actor_id,))


def kill(actor_or_ref, no_restart: bool = True) -> None:
    """Parity: ``ray.kill`` / ``ray.cancel``."""
    rt = get_runtime()
    if isinstance(actor_or_ref, ActorHandle):
        rt.kill_actor(actor_or_ref._actor_id, no_restart)
    else:
        raise TypeError("kill() expects an actor handle; use cancel() for tasks")
