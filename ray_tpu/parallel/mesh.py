"""Device mesh construction with TPU slice topology awareness.

The canonical axes (scaling-book convention):

* ``data``     — batch (pure DP; gradients all-reduced by XLA)
* ``fsdp``     — batch + parameter sharding (ZeRO-3 equivalent via GSPMD)
* ``tensor``   — within-layer model parallelism (Megatron-style, over ICI)
* ``context``  — sequence/context parallelism (ring attention)
* ``expert``   — MoE expert parallelism
* ``pipeline`` — pipeline stages

The reference has no equivalent; its analogue is the NCCL process-group setup
in ``python/ray/train/torch/config.py:65`` plus app-composed TP/PP
(SURVEY.md §2.3). Here a mesh is the single source of truth for every
parallelism dimension, and XLA inserts the collectives.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_TENSOR = "tensor"
AXIS_CONTEXT = "context"
AXIS_EXPERT = "expert"
AXIS_PIPELINE = "pipeline"

# ICI-friendly ordering: axes that want the most bandwidth (tensor, context)
# are placed innermost so they map onto the torus's nearest-neighbor links.
CANONICAL_ORDER = (
    AXIS_PIPELINE,
    AXIS_DATA,
    AXIS_FSDP,
    AXIS_EXPERT,
    AXIS_CONTEXT,
    AXIS_TENSOR,
)


@dataclass
class MeshConfig:
    """Axis sizes; -1 on at most one axis means "use remaining devices"."""

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    context: int = 1
    expert: int = 1
    pipeline: int = 1

    def sizes(self) -> Dict[str, int]:
        return {
            AXIS_DATA: self.data,
            AXIS_FSDP: self.fsdp,
            AXIS_TENSOR: self.tensor,
            AXIS_CONTEXT: self.context,
            AXIS_EXPERT: self.expert,
            AXIS_PIPELINE: self.pipeline,
        }

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = self.sizes()
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError("at most one axis may be -1")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh axes product {fixed} != device count {n_devices}"
            )
        return sizes


def create_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence] = None,
    drop_trivial_axes: bool = False,
    **axis_sizes: int,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` over the canonical axes.

    ``create_mesh(data=-1, tensor=4)`` → mesh with tensor=4 innermost and all
    remaining devices on data. Uses ``mesh_utils.create_device_mesh`` so the
    assignment follows the physical ICI topology on real TPU slices.
    """
    if config is None:
        config = MeshConfig(**{k: axis_sizes.get(k, 1) for k in MeshConfig().sizes()})
        for k in axis_sizes:
            if k not in config.sizes():
                raise ValueError(f"unknown mesh axis {k}")
    if devices is None:
        devices = jax.devices()
    sizes = config.resolve(len(devices))
    names = [a for a in CANONICAL_ORDER if not (drop_trivial_axes and sizes[a] == 1)]
    shape = [sizes[a] for a in names]
    if math.prod(shape) != len(devices):
        # all axes trivial-dropped but devices remain
        names, shape = [AXIS_DATA], [len(devices)]
    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=list(devices), allow_split_physical_axes=True
        )
    except (ValueError, AssertionError, NotImplementedError):
        dev_array = np.array(list(devices)).reshape(shape)
    return Mesh(dev_array, tuple(names))


def mesh_from_pod_type(pod_type: str, config: Optional[MeshConfig] = None) -> Mesh:
    """Mesh for a full pod slice, e.g. ``v5litepod-64`` → 64-device mesh.
    Validates that the visible devices actually form the named slice."""
    from ray_tpu._private.accelerators import tpu as tpu_accel

    want = tpu_accel.pod_chip_count(pod_type)
    devices = jax.devices()
    if want and len(devices) != want:
        raise ValueError(
            f"pod type {pod_type} has {want} chips but {len(devices)} devices "
            f"are visible. Multi-host slices need jax.distributed initialized "
            f"on every slice host first: use ScalingConfig("
            f"use_jax_distributed=True) in JaxTrainer, or call "
            f"ray_tpu.parallel.distributed.initialize(coord, n_procs, rank) "
            f"directly — afterwards jax.devices() is the global set."
        )
    return create_mesh(config or MeshConfig(data=-1), devices=devices)


def local_device_count() -> int:
    return jax.local_device_count()
