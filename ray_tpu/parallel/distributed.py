"""Multi-host SPMD bootstrap: ``jax.distributed`` over the cluster control
plane.

This is the TPU-native analogue of the reference's NCCL process-group
rendezvous (``python/ray/train/torch/config.py:65`` wired from
``python/ray/train/_internal/backend_executor.py:129``): one JAX process per
slice host joins a coordination service, after which ``jax.devices()`` is the
*global* device set and a single jitted program spans every host — XLA places
the collectives on ICI (SURVEY.md §2.3, §7 step 5).

Two layers:

* :func:`initialize` / :func:`shutdown` — thin, platform-aware wrappers over
  ``jax.distributed`` (on the cpu platform they switch on gloo cross-process
  collectives so virtual multi-host meshes work on one box / in CI);
* :func:`rendezvous_via_kv` — the address-agreement step, riding the cluster
  KV exactly like the TF_CONFIG and torch-gloo rendezvous in
  ``ray_tpu/train/{tensorflow,torch}_trainer.py``.
"""

from __future__ import annotations

import os
import socket
import time
from typing import Optional

_NAMESPACE = "jax_rendezvous"
_initialized = False


def is_initialized() -> bool:
    return _initialized


def free_port() -> int:
    """Reserve an ephemeral port (closed before use; same accepted race as the
    reference's ``setup_address``)."""
    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    *,
    local_device_ids: Optional[list] = None,
) -> None:
    """Join the JAX coordination service.

    After this returns on every process, ``jax.devices()`` is the global
    device list across all processes and jitted programs gang-execute.
    On the cpu platform, gloo cross-process collectives are enabled first
    (the virtual-slice test path; real TPU slices use ICI natively).
    """
    global _initialized
    import jax

    platforms = os.environ.get("JAX_PLATFORMS", "")
    if "cpu" in platforms.split(","):
        try:
            jax.config.update("jax_platforms", platforms)
        except Exception:
            pass
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
        # a reused pool worker may already have run a jax computation
        # (backend init is process-wide and first-use);
        # jax.distributed.initialize refuses once backends exist, so on
        # the virtual-cpu path reset them — the cpu backend rebuilds
        # cheaply and no device buffers can span the reset (this process
        # has not joined a mesh yet)
        try:
            from jax._src import xla_bridge

            if xla_bridge.backends_are_initialized():
                xla_bridge._clear_backends()
        except Exception:
            pass

    kwargs = {}
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )
    _initialized = True


def shutdown() -> None:
    global _initialized
    if not _initialized:
        return
    import jax

    try:
        jax.distributed.shutdown()
    finally:
        _initialized = False


def rendezvous_via_kv(
    rt,
    key: str,
    rank: int,
    world: int,
    *,
    node_ip: str = "127.0.0.1",
    timeout_s: float = 120.0,
) -> str:
    """Agree on a coordinator address through the cluster KV.

    Rank 0 reserves a port and publishes ``ip:port`` under ``key``; everyone
    polls until it appears. Returns the coordinator address. ``rt`` is the
    worker runtime (``ray_tpu._private.worker.get_runtime()``).
    """
    if rank == 0:
        addr = f"{node_ip}:{free_port()}"
        rt.rpc("kv_put", _NAMESPACE, key.encode(), addr.encode(), True)
        return addr
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        raw = rt.rpc("kv_get", _NAMESPACE, key.encode())
        if raw:
            return raw.decode()
        time.sleep(0.05)
    raise RuntimeError(f"jax.distributed rendezvous timed out on key {key!r}")


def release_rendezvous(rt, key: str) -> None:
    """Drop the published coordinator address (rank 0, after shutdown)."""
    try:
        rt.rpc("kv_del", _NAMESPACE, key.encode())
    except Exception:
        pass
