"""Sharding rules: logical axes → mesh axes → NamedSharding.

GSPMD parameter sharding replaces the reference's FSDP/ZeRO wrapper classes
(``python/ray/train/torch/train_loop_utils.py`` prepare_model): annotate
``in_shardings`` and XLA emits the reduce-scatter/all-gather pattern
(SURVEY.md §2.3 row FSDP). Models declare *logical* axis names per parameter
dimension ("embed", "mlp", "heads", …); a rule table maps logical names to
mesh axes, so the same model runs pure-DP, FSDP, TP or combinations by
swapping rules — the jit'd step never changes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ray_tpu.parallel.mesh import (
    AXIS_CONTEXT,
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_TENSOR,
)

# logical dimension name -> mesh axis (or None = replicate). A mesh axis may
# appear in multiple rules only if those logical dims never co-occur in one
# parameter.
Rules = Dict[str, Optional[Union[str, Tuple[str, ...]]]]

# Default rule set for transformer LMs: FSDP over ('data','fsdp') on the
# embed dimension, Megatron TP over 'tensor' on heads/mlp/vocab.
DEFAULT_LM_RULES: Rules = {
    "batch": (AXIS_DATA, AXIS_FSDP),
    "sequence": AXIS_CONTEXT,
    "embed": AXIS_FSDP,
    "heads": AXIS_TENSOR,
    "kv_heads": AXIS_TENSOR,
    "mlp": AXIS_TENSOR,
    "vocab": AXIS_TENSOR,
    "expert": AXIS_EXPERT,
    "head_dim": None,
    "layers": None,
    "norm": None,
}


def logical_to_mesh_spec(
    logical_axes: Sequence[Optional[str]], rules: Rules, mesh: Mesh
) -> PartitionSpec:
    """One parameter's logical axes → PartitionSpec, skipping axes absent
    from the mesh or trivially sized (so tests on small meshes just work)."""
    used = set()
    out: List[Optional[Union[str, Tuple[str, ...]]]] = []
    for name in logical_axes:
        mesh_axis = rules.get(name) if name is not None else None
        if mesh_axis is None:
            out.append(None)
            continue
        axes = mesh_axis if isinstance(mesh_axis, tuple) else (mesh_axis,)
        kept = tuple(
            a
            for a in axes
            if a in mesh.axis_names and mesh.shape[a] > 1 and a not in used
        )
        used.update(kept)
        if not kept:
            out.append(None)
        elif len(kept) == 1:
            out.append(kept[0])
        else:
            out.append(kept)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def infer_param_sharding(
    logical_tree: Any, rules: Rules, mesh: Mesh
) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of NamedSharding."""
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, logical_to_mesh_spec(axes, rules, mesh)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(a, (str, type(None))) for a in x),
    )


def batch_sharding(mesh: Mesh, rules: Rules = DEFAULT_LM_RULES) -> NamedSharding:
    """Sharding for (batch, sequence, ...) data arrays."""
    return NamedSharding(
        mesh, logical_to_mesh_spec(["batch", "sequence"], rules, mesh)
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def with_sharding(mesh: Mesh, value: Any, sharding: Any) -> Any:
    """device_put a pytree with per-leaf shardings (sharding may be a single
    NamedSharding or a matching pytree)."""
    if isinstance(sharding, (NamedSharding,)):
        return jax.device_put(value, sharding)
    return jax.tree.map(lambda v, s: jax.device_put(v, s), value, sharding)


def shard_params(params: Any, logical_tree: Any, rules: Rules, mesh: Mesh) -> Any:
    shardings = infer_param_sharding(logical_tree, rules, mesh)
    return jax.tree.map(lambda p, s: jax.device_put(p, s), params, shardings)
