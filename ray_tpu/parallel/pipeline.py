"""Pipeline parallelism over the ``pipeline`` mesh axis.

The reference has no in-tree PP; its building block is compiled DAGs with
NCCL p2p channels between actors (``compiled_dag_node.py:391``,
``torch_tensor_nccl_channel.py`` — SURVEY.md §2.3). TPU-native design: the
whole pipeline is ONE jitted SPMD program; each device on the ``pipeline``
axis holds one stage's parameters, microbatches circulate stage-to-stage with
``ppermute`` (ICI neighbor transfers), GPipe-schedule over M microbatches in
M + P - 1 ticks. XLA overlaps the permute with the next tick's compute.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel._shard_map import shard_map as _shard_map


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    *,
    axis_name: str = "pipeline",
) -> jax.Array:
    """Run inside shard_map: this device applies its stage to the stream.

    ``stage_params``: this device's stage parameters (leading stage axis
    already split by shard_map). ``microbatches``: (M, mb, ...) — the same
    full input on every stage (stage 0 consumes it; later stages consume
    their ppermute'd inputs). Returns (M, mb, ...) outputs valid on the LAST
    stage (zeros elsewhere).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage_idx = jax.lax.axis_index(axis_name)
    M = microbatches.shape[0]
    total_ticks = M + n_stages - 1

    out_shape = jax.eval_shape(lambda x: stage_fn(stage_params, x), microbatches[0])
    outputs0 = jnp.zeros((M,) + tuple(out_shape.shape), out_shape.dtype)
    state0 = jnp.zeros(out_shape.shape, out_shape.dtype)
    if hasattr(jax.lax, "pcast"):
        outputs0 = jax.lax.pcast(outputs0, (axis_name,), to="varying")
        state0 = jax.lax.pcast(state0, (axis_name,), to="varying")

    def tick(carry, t):
        outputs, incoming = carry
        # stage 0 injects microbatch t (while t < M); other stages take the
        # activation forwarded from stage-1 last tick
        mb_idx = jnp.clip(t, 0, M - 1)
        inject = jnp.asarray(microbatches[mb_idx], out_shape.dtype)
        x = jnp.where(stage_idx == 0, inject.astype(out_shape.dtype), incoming)
        y = stage_fn(stage_params, x)
        # last stage records microbatch t - (P-1) when in range
        out_idx = t - (n_stages - 1)
        write = (stage_idx == n_stages - 1) & (out_idx >= 0) & (out_idx < M)
        outputs = jax.lax.cond(
            write,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.clip(out_idx, 0, M - 1), 0
            ),
            lambda o: o,
            outputs,
        )
        # forward activations one hop around the ring
        fwd = jax.lax.ppermute(
            y, axis_name, [(i, (i + 1) % n_stages) for i in range(n_stages)]
        )
        return (outputs, fwd), None

    (outputs, _), _ = jax.lax.scan(
        tick, (outputs0, state0), jnp.arange(total_ticks)
    )
    return outputs


def make_pipeline_fn(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    *,
    axis_name: str = "pipeline",
    params_stage_axis: int = 0,
):
    """Build a global-array pipeline function.

    ``stage_fn(stage_params, x) -> y`` must be shape-preserving (x and y share
    shape/dtype) so activations can circulate the ring. Stacked params have a
    leading stage dimension sharded over the pipeline axis; microbatches are
    replicated in, outputs gathered from the last stage.
    """
    pspec = P(axis_name)
    mspec = P()  # microbatches replicated; stage 0 consumes

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(pspec, mspec),
        out_specs=P(axis_name),
        axis_names={axis_name},
    )
    def run(stacked_params, microbatches):
        my_params = jax.tree.map(lambda p: p[0], stacked_params)
        out = pipeline_apply(
            stage_fn, my_params, microbatches, axis_name=axis_name
        )
        return out[None]  # (1, M, ...) per stage; global (P, M, ...)

    def pipeline(stacked_params, microbatches):
        all_stage_outputs = run(stacked_params, microbatches)
        return all_stage_outputs[-1]  # only the last stage's outputs are real

    return pipeline
