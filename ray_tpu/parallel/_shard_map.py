"""shard_map compatibility across the jax API split.

The repo targets the new-API ``jax.shard_map(..., axis_names=)`` (partial
manual: only the named axes go manual, everything else stays under GSPMD).
jax 0.4.x only ships ``jax.experimental.shard_map.shard_map`` where the same
contract is spelled as the complement — ``auto=`` names the axes that STAY
automatic — and mixing manual+auto requires ``check_rep=False`` (the 0.4.x
replication checker also predates the vma typing these ring-ppermute
kernels rely on, so the check stays off on the legacy path).
"""

from __future__ import annotations

from typing import Optional, Set

import jax


def shard_map(f, *, mesh, in_specs, out_specs, axis_names: Optional[Set] = None):
    """``jax.shard_map`` when available; else the ``jax.experimental``
    spelling with ``auto`` = mesh axes minus ``axis_names``.

    ``axis_names=None`` means fully manual over every mesh axis (both APIs'
    default).
    """
    if hasattr(jax, "shard_map"):
        kwargs = {}
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    # Legacy fallback goes FULLY manual (auto=∅) even for partial-manual
    # call sites: 0.4.x cannot lower axis_index/partition-id with a
    # non-empty auto set. The in/out specs don't name the other axes, so
    # sharding on them is gathered at entry and restored at exit — correct,
    # just without the partial-manual overlap the new API gives.
    return _legacy_shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=False,
    )
