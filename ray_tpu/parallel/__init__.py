"""Parallelism layer: meshes, sharding rules, ring collectives, pipelines.

This is where the framework diverges hardest from the reference: instead of
NCCL process groups (``python/ray/util/collective``, ``train/torch/config.py:65``)
and actor-composed TP/PP (``python/ray/dag/compiled_dag_node.py:391``),
parallelism is expressed as GSPMD mesh axes inside compiled XLA programs over
ICI (SURVEY.md §2.3).
"""

from ray_tpu.parallel.mesh import (
    AXIS_CONTEXT,
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_PIPELINE,
    AXIS_TENSOR,
    MeshConfig,
    create_mesh,
)
from ray_tpu.parallel.sharding import (
    batch_sharding,
    infer_param_sharding,
    logical_to_mesh_spec,
    replicated,
    with_sharding,
)
from ray_tpu.parallel import distributed

__all__ = [
    "AXIS_DATA",
    "AXIS_FSDP",
    "AXIS_TENSOR",
    "AXIS_CONTEXT",
    "AXIS_EXPERT",
    "AXIS_PIPELINE",
    "MeshConfig",
    "create_mesh",
    "batch_sharding",
    "replicated",
    "with_sharding",
    "logical_to_mesh_spec",
    "infer_param_sharding",
    "distributed",
]
