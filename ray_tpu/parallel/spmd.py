"""SPMD train-step builder: one jit'd program over the whole mesh.

This is the TPU-native replacement for the reference's
DataParallelTrainer/NCCL stack (``python/ray/train/data_parallel_trainer.py:25``,
``torch/config.py:65``): instead of N processes exchanging NCCL messages, the
train step is a single XLA program whose in_shardings place batch on
``(data, fsdp)``, parameters on ``fsdp``/``tensor``, and sequence on
``context``; XLA inserts the reduce-scatter/all-gather/psum pattern over ICI.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ray_tpu.models import transformer as tfm
from ray_tpu.parallel.mesh import AXIS_CONTEXT
from ray_tpu.parallel.sharding import (
    DEFAULT_LM_RULES,
    Rules,
    batch_sharding,
    infer_param_sharding,
    logical_to_mesh_spec,
    replicated,
)


@dataclass
class TrainStepBundle:
    """Everything a trainer worker needs to run sharded steps."""

    mesh: Mesh
    init_fn: Callable[[jax.Array], Any]  # key -> sharded TrainState
    step_fn: Callable[[Any, jax.Array, jax.Array], Tuple[Any, Dict[str, jax.Array]]]
    param_shardings: Any
    batch_shard: NamedSharding
    config: Any

    init_seed_fn: Optional[Callable[[int], Any]] = None

    def init_state(self, seed: int = 0):
        """Initialize the sharded train state from an integer seed.

        Multi-host safe: the PRNG key is derived *inside* the jitted program
        from the static seed, so there are no host-local array inputs — every
        process traces the identical program and XLA materializes each
        parameter shard on its owner. Prefer this over ``init_fn(PRNGKey)``
        when the mesh spans processes.
        """
        if self.init_seed_fn is not None:
            return self.init_seed_fn(seed)
        return self.init_fn(jax.random.PRNGKey(seed))

    def shard_batch(self, tokens, targets):
        return (
            put_global(tokens, self.batch_shard),
            put_global(targets, self.batch_shard),
        )


def put_global(host_array, sharding: NamedSharding):
    """Place a host array under ``sharding``, including meshes that span
    processes (multi-host SPMD): every process passes the same *global* value
    and only its addressable shards are materialized. Single-host shardings
    take the fast batched ``device_put`` path."""
    if sharding.is_fully_addressable:
        return jax.device_put(host_array, sharding)
    import numpy as np

    host_array = np.asarray(host_array)
    return jax.make_array_from_callback(
        host_array.shape, sharding, lambda idx: host_array[idx]
    )


def build_lm_train_step(
    cfg: tfm.TransformerConfig,
    mesh: Mesh,
    *,
    rules: Rules = DEFAULT_LM_RULES,
    optimizer: Optional[optax.GradientTransformation] = None,
    learning_rate: float = 1e-4,
    context_parallel: bool = False,
) -> TrainStepBundle:
    """Build init/step functions jitted over ``mesh`` for the LM in
    ``ray_tpu.models.transformer``."""
    if optimizer is None:
        optimizer = optax.adamw(learning_rate, weight_decay=0.01)

    logical = tfm.param_logical_axes(cfg)
    p_shard = infer_param_sharding(logical, rules, mesh)
    b_shard = batch_sharding(mesh, rules)
    ctx_axis = (
        AXIS_CONTEXT
        if context_parallel and AXIS_CONTEXT in mesh.axis_names and mesh.shape[AXIS_CONTEXT] > 1
        else None
    )

    def constrain(params):
        return jax.tree.map(jax.lax.with_sharding_constraint, params, p_shard)

    def init(key):
        params = constrain(tfm.init_params(key, cfg))
        # optimizer moments inherit the param shardings via XLA propagation
        opt_state = optimizer.init(params)
        return {"params": params, "opt": opt_state, "step": jnp.zeros((), jnp.int32)}

    if ctx_axis is not None:
        # ring attention over the context axis (partial-manual shard_map inside
        # the jitted program); RoPE sees global positions, attention the ring
        def loss(params, tokens, targets):
            return tfm.loss_fn(
                params, tokens, targets, cfg, context_axis=ctx_axis, mesh=mesh
            )
    else:
        def loss(params, tokens, targets):
            return tfm.loss_fn(params, tokens, targets, cfg)

    def step(state, tokens, targets):
        lossval, grads = jax.value_and_grad(loss)(state["params"], tokens, targets)
        grads = constrain(grads)
        updates, new_opt = optimizer.update(grads, state["opt"], state["params"])
        new_params = constrain(optax.apply_updates(state["params"], updates))
        gnorm = optax.global_norm(grads)
        return (
            {"params": new_params, "opt": new_opt, "step": state["step"] + 1},
            {"loss": lossval, "grad_norm": gnorm},
        )

    # shardings flow: init commits params with p_shard (constraint inside the
    # program), step infers in_shardings from the committed state + batch
    init_jit = jax.jit(init)
    step_jit = jax.jit(step, donate_argnums=(0,))
    # seed-static variant: no array inputs, so it is valid on meshes that
    # span processes (a host-local PRNGKey array would not be)
    init_seed_jit = jax.jit(
        lambda seed: init(jax.random.PRNGKey(seed)), static_argnums=0
    )

    return TrainStepBundle(
        mesh=mesh,
        init_fn=init_jit,
        step_fn=step_jit,
        param_shardings=p_shard,
        batch_shard=b_shard,
        config=cfg,
        init_seed_fn=init_seed_jit,
    )
