// Demo/driver binary for the C++ API frontend (parity role:
// cpp/src/ray/test/examples in the reference).
//
// Usage: ray_tpu_cpp_demo <host> <port> <auth_key_hex_or_plain>
//
// Connects as a remote driver, prints cluster resources, round-trips an
// object, and (if an actor named "cpp_demo" exists) calls its "ping" method.
// Exits 0 on success; prints MACHINE-readable "OK <step>" lines so a test
// harness can assert each step.

#include <cstdio>
#include <cstdlib>
#include <string>

#include "ray_tpu_client.h"

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s <host> <port> <auth_key>\n", argv[0]);
    return 2;
  }
  std::string host = argv[1];
  int port = atoi(argv[2]);
  std::string key = argv[3];

  ray_tpu::Client client;
  std::string err;
  if (!client.Connect(host, port, key, &err)) {
    fprintf(stderr, "connect failed: %s\n", err.c_str());
    return 1;
  }
  printf("OK connect\n");

  std::map<std::string, double> resources;
  if (!client.ClusterResources(&resources, &err)) {
    fprintf(stderr, "cluster_resources failed: %s\n", err.c_str());
    return 1;
  }
  printf("OK cluster_resources CPU=%.1f\n", resources["CPU"]);

  // put/get round trip
  std::string oid;
  ray_tpu::PyValue payload = ray_tpu::PyValue::Str("hello from c++");
  if (!client.Put(payload, &oid, &err)) {
    fprintf(stderr, "put failed: %s\n", err.c_str());
    return 1;
  }
  ray_tpu::PyValue back;
  if (!client.Get(oid, 30.0, &back, &err)) {
    fprintf(stderr, "get failed: %s\n", err.c_str());
    return 1;
  }
  if (back.kind != ray_tpu::PyValue::Kind::kStr || back.s != "hello from c++") {
    fprintf(stderr, "roundtrip mismatch\n");
    return 1;
  }
  printf("OK put_get\n");

  // zero-copy local data plane: a 1 MiB payload lands in the head's shm
  // arena; GetLocalShm maps it and reads without a socket round trip
  {
    std::string big(1 << 20, '\0');
    for (size_t i = 0; i < big.size(); i++) big[i] = char(i * 131 % 251);
    std::string big_oid;
    if (!client.Put(ray_tpu::PyValue::Bytes(big), &big_oid, &err)) {
      fprintf(stderr, "big put failed: %s\n", err.c_str());
      return 1;
    }
    ray_tpu::PyValue local;
    if (client.GetLocal(big_oid, &local, &err)) {
      if (local.kind != ray_tpu::PyValue::Kind::kBytes || local.s != big) {
        fprintf(stderr, "shm_get mismatch (kind=%d size=%zu)\n",
                int(local.kind), local.s.size());
        return 1;
      }
      printf("OK shm_get %zu bytes\n", local.s.size());
    } else if (err.empty()) {
      printf("SKIP shm_get (no same-machine copy)\n");
    } else {
      fprintf(stderr, "shm_get failed: %s\n", err.c_str());
      return 1;
    }
  }

  // named-actor call (the harness registers "cpp_demo" with method add)
  std::string result_oid;
  std::vector<ray_tpu::PyValue> args{ray_tpu::PyValue::Int(40),
                                     ray_tpu::PyValue::Int(2)};
  if (client.CallActor("cpp_demo", "add", args, &result_oid, &err)) {
    ray_tpu::PyValue result;
    if (!client.Get(result_oid, 60.0, &result, &err)) {
      fprintf(stderr, "actor result get failed: %s\n", err.c_str());
      return 1;
    }
    if (result.kind != ray_tpu::PyValue::Kind::kInt || result.i != 42) {
      fprintf(stderr, "actor result mismatch (kind=%d i=%lld)\n",
              int(result.kind), (long long)result.i);
      return 1;
    }
    printf("OK call_actor 42\n");
  } else {
    printf("SKIP call_actor (%s)\n", err.c_str());
  }

  // repeated-container reply: the harness actor's dup() returns [d, d] with
  // d a non-empty dict, so the pickle stream memoizes d before filling it
  // and references it via BINGET — both decoded copies must carry the items
  std::string dup_oid;
  if (client.CallActor("cpp_demo", "dup", {}, &dup_oid, &err)) {
    ray_tpu::PyValue dup;
    if (!client.Get(dup_oid, 60.0, &dup, &err)) {
      fprintf(stderr, "dup result get failed: %s\n", err.c_str());
      return 1;
    }
    bool ok = dup.items.size() == 2;
    for (const auto& d : dup.items) {
      const ray_tpu::PyValue* v = d.DictGet("k");
      ok = ok && v != nullptr && v->items.size() == 3 && v->items[2].i == 3;
    }
    if (!ok) {
      fprintf(stderr, "memoized container decoded wrong\n");
      return 1;
    }
    printf("OK memo_roundtrip\n");
  } else {
    printf("SKIP memo_roundtrip (%s)\n", err.c_str());
  }

  client.Close();
  printf("OK done\n");
  return 0;
}
