// Implementation of the C++ API frontend (see ray_tpu_client.h).
//
// Wire stack, bottom-up:
//   1. TCP socket.
//   2. multiprocessing.connection framing: !i length prefix (-1 sentinel +
//      !Q for >2**31-1 payloads).
//   3. Challenge auth (CPython 3.12 scheme): both sides exchange
//      b"#CHALLENGE#{digest}<random>" and answer with
//      b"{digest}" + HMAC(authkey, challenge-after-prefix). SHA-256 based.
//   4. Messages: pickled Python tuples. A minimal pickler (protocol 3) emits
//      requests; a minimal unpickler decodes the reply subset (protocol 4/5
//      opcodes observed from CPython's default pickler).

#include "ray_tpu_client.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <random>
#include <stdexcept>
#include <thread>

#include "../native/rt_store.h"

namespace ray_tpu {

// ---------------------------------------------------------------------------
// SHA-256 + HMAC (FIPS 180-4 / RFC 2104; public standard algorithms)
// ---------------------------------------------------------------------------

namespace {

struct Sha256 {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buflen = 0;

  Sha256() {
    static const uint32_t init[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                     0xa54ff53a, 0x510e527f, 0x9b05688c,
                                     0x1f83d9ab, 0x5be0cd19};
    memcpy(h, init, sizeof(init));
  }

  static uint32_t Rot(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  void Block(const uint8_t* p) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
        0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
        0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
        0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
        0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
        0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
        0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
        0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
        0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = Rot(w[i - 15], 7) ^ Rot(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = Rot(w[i - 2], 17) ^ Rot(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
             g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t s1 = Rot(e, 6) ^ Rot(e, 11) ^ Rot(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + s1 + ch + k[i] + w[i];
      uint32_t s0 = Rot(a, 2) ^ Rot(a, 13) ^ Rot(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      hh = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void Update(const uint8_t* p, size_t n) {
    len += n;
    while (n) {
      size_t take = std::min(n, sizeof(buf) - buflen);
      memcpy(buf + buflen, p, take);
      buflen += take;
      p += take;
      n -= take;
      if (buflen == 64) {
        Block(buf);
        buflen = 0;
      }
    }
  }

  void Final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    Update(&pad, 1);
    uint8_t zero = 0;
    while (buflen != 56) Update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; i++) lenb[i] = uint8_t(bits >> (56 - 8 * i));
    Update(lenb, 8);
    for (int i = 0; i < 8; i++) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

std::string HmacSha256(const std::string& key, const std::string& msg) {
  uint8_t k[64] = {0};
  if (key.size() > 64) {
    Sha256 kh;
    kh.Update(reinterpret_cast<const uint8_t*>(key.data()), key.size());
    kh.Final(k);
  } else {
    memcpy(k, key.data(), key.size());
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  Sha256 inner;
  inner.Update(ipad, 64);
  inner.Update(reinterpret_cast<const uint8_t*>(msg.data()), msg.size());
  uint8_t ih[32];
  inner.Final(ih);
  Sha256 outer;
  outer.Update(opad, 64);
  outer.Update(ih, 32);
  uint8_t oh[32];
  outer.Final(oh);
  return std::string(reinterpret_cast<char*>(oh), 32);
}

bool ConstantTimeEq(const std::string& a, const std::string& b) {
  if (a.size() != b.size()) return false;
  unsigned char acc = 0;
  for (size_t i = 0; i < a.size(); i++) acc |= a[i] ^ b[i];
  return acc == 0;
}

// ---------------------------------------------------------------------------
// Mini pickler (protocol 3 requests; loadable by any modern CPython)
// ---------------------------------------------------------------------------

class Pickler {
 public:
  Pickler() { out_ += "\x80\x03"; }  // PROTO 3

  void None() { out_ += 'N'; }
  void Bool(bool v) { out_ += v ? '\x88' : '\x89'; }  // NEWTRUE/NEWFALSE

  void Int(int64_t v) {
    if (v >= 0 && v < 256) {
      out_ += 'K';  // BININT1
      out_ += char(uint8_t(v));
    } else if (v >= INT32_MIN && v <= INT32_MAX) {
      out_ += 'J';  // BININT (4-byte LE signed)
      AppendLE32(uint32_t(int32_t(v)));
    } else {
      out_ += '\x8a';  // LONG1
      uint8_t bytes[9];
      int n = 0;
      uint64_t uv = uint64_t(v);
      // two's-complement little-endian, minimal width
      for (n = 1; n <= 8; n++) {
        int64_t trunc = int64_t(uv << (64 - 8 * n)) >> (64 - 8 * n);
        if (trunc == v) break;
      }
      out_ += char(uint8_t(n));
      for (int i = 0; i < n; i++) bytes[i] = uint8_t(uv >> (8 * i));
      out_.append(reinterpret_cast<char*>(bytes), n);
    }
  }

  void Float(double v) {
    out_ += 'G';  // BINFLOAT: big-endian double
    uint64_t bits;
    memcpy(&bits, &v, 8);
    for (int i = 7; i >= 0; i--) out_ += char(uint8_t(bits >> (8 * i)));
  }

  void Str(const std::string& s) {
    out_ += 'X';  // BINUNICODE (utf-8, 4-byte LE length)
    AppendLE32(uint32_t(s.size()));
    out_ += s;
  }

  void Bytes(const std::string& b) {
    if (b.size() < 256) {
      out_ += 'C';  // SHORT_BINBYTES
      out_ += char(uint8_t(b.size()));
    } else {
      out_ += 'B';  // BINBYTES
      AppendLE32(uint32_t(b.size()));
    }
    out_ += b;
  }

  void Mark() { out_ += '('; }
  void Tuple() { out_ += 't'; }    // from mark
  void Tuple1() { out_ += '\x85'; }
  void Tuple2() { out_ += '\x86'; }
  void Tuple3() { out_ += '\x87'; }
  void EmptyTuple() { out_ += ')'; }

  // GLOBAL ray_tpu._private.ids ObjectID ; TUPLE1(bytes) ; REDUCE
  void ObjectId(const std::string& bin) {
    out_ += 'c';
    out_ += "ray_tpu._private.ids\nObjectID\n";
    Bytes(bin);
    Tuple1();
    out_ += 'R';  // REDUCE
  }

  void Value(const PyValue& v) {
    switch (v.kind) {
      case PyValue::Kind::kNone: None(); break;
      case PyValue::Kind::kBool: Bool(v.b); break;
      case PyValue::Kind::kInt: Int(v.i); break;
      case PyValue::Kind::kFloat: Float(v.f); break;
      case PyValue::Kind::kStr: Str(v.s); break;
      case PyValue::Kind::kBytes: Bytes(v.s); break;
      case PyValue::Kind::kTuple:
      case PyValue::Kind::kList: {
        Mark();
        for (const auto& it : v.items) Value(it);
        if (v.kind == PyValue::Kind::kTuple) {
          Tuple();
        } else {
          out_ += 'l';  // LIST from mark
        }
        break;
      }
      case PyValue::Kind::kDict: {
        out_ += '}';  // EMPTY_DICT
        Mark();
        for (const auto& kv : v.dict) {
          Value(kv.first);
          Value(kv.second);
        }
        out_ += 'u';  // SETITEMS
        break;
      }
      case PyValue::Kind::kObject:
        throw std::runtime_error("cannot pickle opaque object value");
    }
  }

  std::string Finish() {
    std::string r = out_;
    r += '.';  // STOP
    return r;
  }

 private:
  void AppendLE32(uint32_t v) {
    for (int i = 0; i < 4; i++) out_ += char(uint8_t(v >> (8 * i)));
  }
  std::string out_;
};

// ---------------------------------------------------------------------------
// Mini unpickler: the opcode subset CPython's default pickler emits for the
// tuples/dicts/bytes/str/num replies this protocol carries.
// ---------------------------------------------------------------------------

class Unpickler {
 public:
  explicit Unpickler(const std::string& data) : d_(data) {}

  PyValue Load() {
    while (true) {
      if (pos_ >= d_.size()) throw std::runtime_error("pickle truncated");
      uint8_t op = uint8_t(d_[pos_++]);
      switch (op) {
        case 0x80: pos_ += 1; break;                      // PROTO
        case 0x95: pos_ += 8; break;                      // FRAME
        case '.':                                          // STOP
          if (stack_.empty()) throw std::runtime_error("empty pickle stack");
          return stack_.back();
        case 'N': Push(PyValue::None()); break;           // NONE
        case 0x88: Push(PyValue::Bool(true)); break;      // NEWTRUE
        case 0x89: Push(PyValue::Bool(false)); break;     // NEWFALSE
        case 'K': Push(PyValue::Int(U8())); break;        // BININT1
        case 'M': Push(PyValue::Int(U16())); break;       // BININT2
        case 'J': Push(PyValue::Int(int32_t(U32()))); break;  // BININT
        case 0x8a: {                                      // LONG1
          size_t n = U8();
          int64_t v = 0;
          for (size_t i = 0; i < n; i++)
            v |= int64_t(uint8_t(Next())) << (8 * i);
          if (n > 0 && n < 8 && (uint8_t(d_[pos_ - 1]) & 0x80))
            v |= int64_t(~uint64_t(0) << (8 * n));  // sign-extend
          Push(PyValue::Int(v));
          break;
        }
        case 'G': {                                       // BINFLOAT (BE)
          uint64_t bits = 0;
          for (int i = 0; i < 8; i++) bits = (bits << 8) | uint8_t(Next());
          double v;
          memcpy(&v, &bits, 8);
          Push(PyValue::Float(v));
          break;
        }
        case 0x8c: Push(PyValue::Str(Take(U8()))); break;     // SHORT_BINUNICODE
        case 'X': Push(PyValue::Str(Take(U32()))); break;     // BINUNICODE
        case 'C': Push(PyValue::Bytes(Take(U8()))); break;    // SHORT_BINBYTES
        case 'B': Push(PyValue::Bytes(Take(U32()))); break;   // BINBYTES
        case 0x8e: Push(PyValue::Bytes(Take(U64()))); break;  // BINBYTES8
        case ')': PushTuple(0); break;                    // EMPTY_TUPLE
        case 0x85: PushTuple(1); break;                   // TUPLE1
        case 0x86: PushTuple(2); break;                   // TUPLE2
        case 0x87: PushTuple(3); break;                   // TUPLE3
        case '(': marks_.push_back(stack_.size()); break; // MARK
        case 't': {                                       // TUPLE
          size_t m = PopMark();
          PyValue t;
          t.kind = PyValue::Kind::kTuple;
          t.items.assign(stack_.begin() + m, stack_.end());
          FlushDropMemoSrcsFrom(m);
          stack_.resize(m);
          Push(std::move(t));
          break;
        }
        case ']': {                                       // EMPTY_LIST
          PyValue l;
          l.kind = PyValue::Kind::kList;
          Push(std::move(l));
          break;
        }
        case 'e': {                                       // APPENDS
          size_t m = PopMark();
          auto& list = stack_[m - 1];
          for (size_t i = m; i < stack_.size(); i++)
            list.items.push_back(stack_[i]);
          FlushDropMemoSrcsFrom(m);
          stack_.resize(m);
          MarkMemoDirtyAt(m - 1);
          break;
        }
        case 'a': {                                       // APPEND
          PyValue v = Pop();
          stack_.back().items.push_back(std::move(v));
          MarkMemoDirtyAt(stack_.size() - 1);
          break;
        }
        case '}': {                                       // EMPTY_DICT
          PyValue d;
          d.kind = PyValue::Kind::kDict;
          Push(std::move(d));
          break;
        }
        case 'u': {                                       // SETITEMS
          size_t m = PopMark();
          auto& dict = stack_[m - 1];
          for (size_t i = m; i + 1 < stack_.size(); i += 2)
            dict.dict.emplace_back(stack_[i], stack_[i + 1]);
          FlushDropMemoSrcsFrom(m);
          stack_.resize(m);
          MarkMemoDirtyAt(m - 1);
          break;
        }
        case 's': {                                       // SETITEM
          PyValue v = Pop();
          PyValue k = Pop();
          stack_.back().dict.emplace_back(std::move(k), std::move(v));
          MarkMemoDirtyAt(stack_.size() - 1);
          break;
        }
        case 0x94:                                        // MEMOIZE
          memo_.push_back(stack_.back());
          RecordMemoSrc(memo_.size() - 1);
          break;
        case 'q': memo_put(U8()); break;                  // BINPUT
        case 'r': memo_put(U32()); break;                 // LONG_BINPUT
        case 'h': Push(MemoGet(U8())); break;             // BINGET
        case 'j': Push(MemoGet(U32())); break;            // LONG_BINGET
        case 0x93: {                                      // STACK_GLOBAL
          PyValue name = Pop();
          PyValue mod = Pop();
          PyValue o;
          o.kind = PyValue::Kind::kObject;
          o.repr = mod.s + "." + name.s;
          Push(std::move(o));
          break;
        }
        case 'c': {                                       // GLOBAL
          std::string mod = Line(), name = Line();
          PyValue o;
          o.kind = PyValue::Kind::kObject;
          o.repr = mod + "." + name;
          Push(std::move(o));
          break;
        }
        case 'R': {                                       // REDUCE
          PyValue args = Pop();
          PyValue callee = Pop();
          PyValue o;
          o.kind = PyValue::Kind::kObject;
          o.repr = callee.repr + "(";
          for (size_t i = 0; i < args.items.size(); i++) {
            if (i) o.repr += ", ";
            const auto& a = args.items[i];
            if (a.kind == PyValue::Kind::kStr) o.repr += a.s;
            else if (a.kind == PyValue::Kind::kInt)
              o.repr += std::to_string(a.i);
            else o.repr += "...";
          }
          o.repr += ")";
          Push(std::move(o));
          break;
        }
        case 'b': {                                       // BUILD
          Pop();  // state: drop, keep the object summary
          break;
        }
        case 0x81: {                                      // NEWOBJ
          PyValue args = Pop();
          PyValue cls = Pop();
          PyValue o;
          o.kind = PyValue::Kind::kObject;
          o.repr = cls.repr + "(...)";
          (void)args;
          Push(std::move(o));
          break;
        }
        default:
          throw std::runtime_error("unsupported pickle opcode " +
                                   std::to_string(int(op)));
      }
    }
  }

 private:
  char Next() {
    if (pos_ >= d_.size()) throw std::runtime_error("pickle truncated");
    return d_[pos_++];
  }
  uint64_t U8() { return uint8_t(Next()); }
  uint64_t U16() {
    uint64_t v = U8();
    return v | (U8() << 8);
  }
  uint64_t U32() {
    uint64_t v = 0;
    for (int i = 0; i < 4; i++) v |= U8() << (8 * i);
    return v;
  }
  uint64_t U64() {
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v |= U8() << (8 * i);
    return v;
  }
  std::string Take(size_t n) {
    if (pos_ + n > d_.size()) throw std::runtime_error("pickle truncated");
    std::string s = d_.substr(pos_, n);
    pos_ += n;
    return s;
  }
  std::string Line() {
    std::string s;
    while (true) {
      char c = Next();
      if (c == '\n') return s;
      s += c;
    }
  }
  void Push(PyValue v) { stack_.push_back(std::move(v)); }
  PyValue Pop() {
    FlushDropMemoSrcsFrom(stack_.size() - 1);
    PyValue v = std::move(stack_.back());
    stack_.pop_back();
    return v;
  }
  void PushTuple(size_t n) {
    PyValue t;
    t.kind = PyValue::Kind::kTuple;
    t.items.assign(stack_.end() - n, stack_.end());
    FlushDropMemoSrcsFrom(stack_.size() - n);
    stack_.resize(stack_.size() - n);
    Push(std::move(t));
  }
  size_t PopMark() {
    size_t m = marks_.back();
    marks_.pop_back();
    return m;
  }
  // CPython memoizes containers BEFORE filling them (EMPTY_DICT, MEMOIZE,
  // then SETITEMS). The memo here is by-value, so each memo slot remembers
  // which stack position it snapshotted; a mutation only marks the slot
  // dirty (O(1) amortized), and the re-snapshot is taken lazily — on the
  // next BINGET of the slot, or when the container leaves the stack. This
  // keeps decode linear: a large list arriving as many APPENDS batches is
  // copied at most once per actual reuse, not once per batch.
  // (Self-referential containers remain out of scope for this by-value
  // model; protocol replies are plain data.)
  struct MemoSrc {
    size_t pos;    // stack position snapshotted from
    size_t slot;   // memo slot
    bool dirty;    // container mutated since last snapshot
  };
  void RecordMemoSrc(size_t slot) {
    memo_srcs_.push_back(MemoSrc{stack_.size() - 1, slot, false});
  }
  // snapshot any dirty slots whose source is about to leave the stack, then
  // drop their tracking. MUST be called while stack_[pos] is still intact.
  void FlushDropMemoSrcsFrom(size_t new_size) {
    memo_srcs_.erase(
        std::remove_if(memo_srcs_.begin(), memo_srcs_.end(),
                       [&](const MemoSrc& ms) {
                         if (ms.pos < new_size) return false;
                         if (ms.dirty) memo_[ms.slot] = stack_[ms.pos];
                         return true;
                       }),
        memo_srcs_.end());
  }
  void MarkMemoDirtyAt(size_t pos) {
    for (auto& ms : memo_srcs_)
      if (ms.pos == pos) ms.dirty = true;
  }
  const PyValue& MemoGet(size_t idx) {
    for (auto& ms : memo_srcs_)
      if (ms.slot == idx && ms.dirty) {
        memo_[idx] = stack_[ms.pos];
        ms.dirty = false;
      }
    return memo_.at(idx);
  }
  void memo_put(size_t idx) {
    if (memo_.size() <= idx) memo_.resize(idx + 1);
    memo_[idx] = stack_.back();
    RecordMemoSrc(idx);
  }

  const std::string& d_;
  size_t pos_ = 0;
  std::vector<PyValue> stack_;
  std::vector<size_t> marks_;
  std::vector<PyValue> memo_;
  // live (stack position, memo slot) tracking entries; dropped (with a
  // final snapshot if dirty) as the stack shrinks past them
  std::vector<MemoSrc> memo_srcs_;
};

}  // namespace

// ---------------------------------------------------------------------------
// PyValue helpers
// ---------------------------------------------------------------------------

PyValue PyValue::None() { return PyValue{}; }
PyValue PyValue::Bool(bool v) {
  PyValue p;
  p.kind = Kind::kBool;
  p.b = v;
  return p;
}
PyValue PyValue::Int(int64_t v) {
  PyValue p;
  p.kind = Kind::kInt;
  p.i = v;
  return p;
}
PyValue PyValue::Float(double v) {
  PyValue p;
  p.kind = Kind::kFloat;
  p.f = v;
  return p;
}
PyValue PyValue::Str(std::string v) {
  PyValue p;
  p.kind = Kind::kStr;
  p.s = std::move(v);
  return p;
}
PyValue PyValue::Bytes(std::string v) {
  PyValue p;
  p.kind = Kind::kBytes;
  p.s = std::move(v);
  return p;
}
const PyValue* PyValue::DictGet(const std::string& key) const {
  for (const auto& kv : dict)
    if (kv.first.kind == Kind::kStr && kv.first.s == key) return &kv.second;
  return nullptr;
}

// ---------------------------------------------------------------------------
// Client impl
// ---------------------------------------------------------------------------

struct Client::Impl {
  int fd = -1;
  std::string auth_key;
  int rpc_seq = 0;
  uint32_t put_counter = 0;
  std::string driver_task_id;  // 24 bytes: synthesized driver task id

  bool SendAll(const char* p, size_t n, std::string* err) {
    while (n) {
      ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
      if (w <= 0) {
        *err = "socket send failed";
        return false;
      }
      p += w;
      n -= size_t(w);
    }
    return true;
  }

  bool RecvAll(char* p, size_t n, std::string* err) {
    while (n) {
      ssize_t r = ::recv(fd, p, n, 0);
      if (r <= 0) {
        *err = "socket recv failed / closed";
        return false;
      }
      p += r;
      n -= size_t(r);
    }
    return true;
  }

  bool SendFrame(const std::string& payload, std::string* err) {
    if (payload.size() > 0x7fffffffULL) {
      char hdr[12];
      int32_t neg = -1;
      uint32_t nbe = htonl(uint32_t(neg));
      memcpy(hdr, &nbe, 4);
      uint64_t n = payload.size();
      for (int i = 0; i < 8; i++) hdr[4 + i] = char(uint8_t(n >> (56 - 8 * i)));
      if (!SendAll(hdr, 12, err)) return false;
    } else {
      uint32_t nbe = htonl(uint32_t(payload.size()));
      char hdr[4];
      memcpy(hdr, &nbe, 4);
      if (!SendAll(hdr, 4, err)) return false;
    }
    return SendAll(payload.data(), payload.size(), err);
  }

  bool RecvFrame(std::string* payload, std::string* err) {
    char hdr[4];
    if (!RecvAll(hdr, 4, err)) return false;
    uint32_t nbe;
    memcpy(&nbe, hdr, 4);
    int64_t n = int32_t(ntohl(nbe));
    if (n == -1) {
      char hdr8[8];
      if (!RecvAll(hdr8, 8, err)) return false;
      n = 0;
      for (int i = 0; i < 8; i++) n = (n << 8) | uint8_t(hdr8[i]);
    }
    payload->resize(size_t(n));
    return RecvAll(payload->data(), size_t(n), err);
  }

  // CPython 3.12 answer_challenge + deliver_challenge (mutual auth).
  bool Authenticate(std::string* err) {
    const std::string kChallenge = "#CHALLENGE#";
    const std::string kWelcome = "#WELCOME#";
    std::string msg;
    if (!RecvFrame(&msg, err)) return false;
    if (msg.rfind(kChallenge, 0) != 0) {
      *err = "protocol error: expected challenge";
      return false;
    }
    std::string challenge = msg.substr(kChallenge.size());
    // challenge is b"{digest}<random>"; MAC covers the whole remainder
    std::string digest_name = "md5";
    if (!challenge.empty() && challenge[0] == '{') {
      size_t close = challenge.find('}');
      if (close != std::string::npos)
        digest_name = challenge.substr(1, close - 1);
    }
    if (digest_name != "sha256") {
      *err = "unsupported auth digest " + digest_name +
             " (this client implements sha256)";
      return false;
    }
    std::string mac = HmacSha256(auth_key, challenge);
    if (!SendFrame("{sha256}" + mac, err)) return false;
    std::string resp;
    if (!RecvFrame(&resp, err)) return false;
    if (resp != kWelcome) {
      *err = "authentication rejected";
      return false;
    }
    // Now the client challenges the server.
    std::string rnd(32, '\0');
    std::random_device rd;
    for (auto& c : rnd) c = char(rd() & 0xff);
    std::string my_challenge = "{sha256}" + rnd;
    if (!SendFrame(kChallenge + my_challenge, err)) return false;
    std::string answer;
    if (!RecvFrame(&answer, err)) return false;
    std::string expect = HmacSha256(auth_key, my_challenge);
    std::string got = answer;
    if (got.rfind("{sha256}", 0) == 0) got = got.substr(8);
    if (!ConstantTimeEq(expect, got)) {
      SendFrame("#FAILURE#", err);
      *err = "server failed our challenge";
      return false;
    }
    return SendFrame(kWelcome, err);
  }

  bool SendMsg(const std::string& pickled, std::string* err) {
    return SendFrame(pickled, err);
  }

  bool RecvMsg(PyValue* out, std::string* err) {
    std::string payload;
    if (!RecvFrame(&payload, err)) return false;
    try {
      Unpickler u(payload);
      *out = u.Load();
    } catch (const std::exception& e) {
      *err = std::string("unpickle failed: ") + e.what();
      return false;
    }
    return true;
  }
};

Client::Client() : impl_(new Impl) {}
Client::~Client() { Close(); }

bool Client::connected() const { return impl_->fd >= 0; }

void Client::Close() {
  if (impl_->fd >= 0) {
    ::close(impl_->fd);
    impl_->fd = -1;
  }
}

bool Client::Connect(const std::string& host, int port,
                     const std::string& auth_key, std::string* error) {
  impl_->auth_key = auth_key;
  struct addrinfo hints {};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_s = std::to_string(port);
  if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) != 0 || !res) {
    *error = "getaddrinfo failed for " + host;
    return false;
  }
  impl_->fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (impl_->fd < 0 ||
      ::connect(impl_->fd, res->ai_addr, res->ai_addrlen) != 0) {
    freeaddrinfo(res);
    Close();
    *error = "connect failed to " + host + ":" + port_s;
    return false;
  }
  freeaddrinfo(res);
  int one = 1;
  setsockopt(impl_->fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  if (!impl_->Authenticate(error)) {
    Close();
    return false;
  }
  // register_driver handshake
  Pickler p;
  p.Mark();
  p.Str("register_driver");
  p.Int(int64_t(::getpid()));
  p.Tuple();
  if (!impl_->SendMsg(p.Finish(), error)) {
    Close();
    return false;
  }
  PyValue reply;
  if (!impl_->RecvMsg(&reply, error)) {
    Close();
    return false;
  }
  if (reply.kind != PyValue::Kind::kTuple || reply.items.size() != 2 ||
      reply.items[0].s != "driver_registered") {
    *error = "unexpected handshake reply";
    Close();
    return false;
  }
  // synthesize this driver's put namespace: TaskID.for_driver(random job)
  std::random_device rd;
  std::string task_id(8, '\0');
  for (auto& c : task_id) c = char(rd() & 0xff);
  task_id += std::string(12, '\0');                  // ActorID zero-unique part
  for (int i = 0; i < 4; i++) task_id += char(rd() & 0xff);  // JobID
  impl_->driver_task_id = task_id;
  return true;
}

bool Client::Rpc(const std::string& op, const std::vector<PyValue>& args,
                 PyValue* result, std::string* error) {
  int req_id = impl_->rpc_seq++;
  Pickler p;
  p.Mark();
  p.Str("rpc");
  p.Int(req_id);
  p.Str(op);
  {
    p.Mark();
    for (const auto& a : args) p.Value(a);
    p.Tuple();
  }
  p.Tuple();
  if (!impl_->SendMsg(p.Finish(), error)) return false;
  // replies are ordered per connection for a client that only issues rpcs
  PyValue reply;
  while (true) {
    if (!impl_->RecvMsg(&reply, error)) return false;
    if (reply.kind == PyValue::Kind::kTuple && reply.items.size() >= 3 &&
        reply.items[0].kind == PyValue::Kind::kStr &&
        reply.items[0].s == "rpc_reply" &&
        reply.items[1].i == req_id) {
      break;
    }
    // ignore unrelated pushed messages (log lines etc.)
  }
  *result = reply.items[2];
  if (result->kind == PyValue::Kind::kObject) {
    *error = "rpc " + op + " raised: " + result->repr;
    return false;
  }
  return true;
}

bool Client::ClusterResources(std::map<std::string, double>* out,
                              std::string* error) {
  PyValue nodes;
  if (!Rpc("list_nodes", {}, &nodes, error)) return false;
  out->clear();
  for (const auto& node : nodes.items) {
    const PyValue* alive = node.DictGet("alive");
    if (alive && alive->kind == PyValue::Kind::kBool && !alive->b) continue;
    const PyValue* total = node.DictGet("total");
    if (!total) continue;
    for (const auto& kv : total->dict) {
      double v = kv.second.kind == PyValue::Kind::kFloat ? kv.second.f
                                                         : double(kv.second.i);
      (*out)[kv.first.s] += v;
    }
  }
  return true;
}

bool Client::Put(const PyValue& value, std::string* object_id,
                 std::string* error) {
  // ObjectID = driver task id + (2^31 + counter) LE
  uint32_t index = 0x80000000u + impl_->put_counter++;
  std::string oid = impl_->driver_task_id;
  for (int i = 0; i < 4; i++) oid += char(uint8_t(index >> (8 * i)));
  // serde blob: [u32 nbufs=0][u64 plen] + pickle(value)
  Pickler vp;
  vp.Value(value);
  std::string pickled = vp.Finish();
  std::string blob(12, '\0');
  uint64_t plen = pickled.size();
  for (int i = 0; i < 8; i++) blob[4 + i] = char(uint8_t(plen >> (8 * i)));
  blob += pickled;
  Pickler p;
  p.Mark();
  p.Str("put_object");
  p.ObjectId(oid);
  p.Bytes(blob);
  p.Tuple();
  if (!impl_->SendMsg(p.Finish(), error)) return false;
  *object_id = oid;
  return true;
}

// Decode the store's flat object frame: <IQ> header (nbufs, pickle length),
// pickle bytes, then 64-byte-aligned out-of-band buffers (rejected here —
// the mini unpickler has no buffer protocol). Shared by Get and GetLocal.
static bool DecodeFrame(const std::string& blob, PyValue* out,
                        std::string* error) {
  if (blob.size() < 12) {
    *error = "malformed object frame";
    return false;
  }
  uint32_t nbufs = 0;
  for (int i = 0; i < 4; i++) nbufs |= uint32_t(uint8_t(blob[i])) << (8 * i);
  uint64_t plen = 0;
  for (int i = 0; i < 8; i++)
    plen |= uint64_t(uint8_t(blob[4 + i])) << (8 * i);
  if (nbufs != 0) {
    *error = "object has out-of-band buffers (numpy); unsupported in the "
             "C++ frontend";
    return false;
  }
  if (blob.size() < 12 + plen) {
    *error = "malformed object frame";
    return false;
  }
  // named lvalue: Unpickler keeps a reference to its input, so a temporary
  // here would dangle for the whole Load()
  std::string pickled = blob.substr(12, plen);
  try {
    Unpickler u(pickled);
    *out = u.Load();
  } catch (const std::exception& e) {
    *error = std::string("object unpickle failed: ") + e.what();
    return false;
  }
  return true;
}

bool Client::Get(const std::string& object_id, double timeout_s, PyValue* out,
                 std::string* error) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_s);
  while (true) {
    PyValue reply;
    std::vector<PyValue> args{PyValue::Bytes(object_id)};
    if (!Rpc("get_object_blob", args, &reply, error)) return false;
    if (reply.kind == PyValue::Kind::kTuple && reply.items.size() == 2) {
      const std::string& tag = reply.items[0].s;
      const std::string& blob = reply.items[1].s;
      if (tag == "err") {
        // error entries hold a raw-pickled exception (no serde frame —
        // they come from pickle.dumps directly, unlike "ok" blobs)
        *error = "task failed";
        try {
          Unpickler u_err(blob);
          PyValue e = u_err.Load();
          if (e.kind == PyValue::Kind::kObject)
            *error = "task failed: " + e.repr;
        } catch (...) {
        }
        return false;
      }
      return DecodeFrame(blob, out, error);
    }
    if (std::chrono::steady_clock::now() > deadline) {
      *error = "get timed out";
      return false;
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
}

// ---- zero-copy local data plane ------------------------------------------

static std::string LocalMachineId() {
  // must byte-match ray_tpu._private.object_transfer.machine_id():
  // "{boot_id}:{hostname}"
  std::string boot;
  FILE* f = fopen("/proc/sys/kernel/random/boot_id", "r");
  if (f) {
    char buf[128];
    if (fgets(buf, sizeof(buf), f)) {
      boot = buf;
      while (!boot.empty() && (boot.back() == '\n' || boot.back() == '\r'))
        boot.pop_back();
    }
    fclose(f);
  }
  char host[256] = {0};
  gethostname(host, sizeof(host) - 1);
  return boot + ":" + host;
}

bool Client::GetLocalShm(const std::string& object_id, std::string* blob,
                         std::string* error) {
  error->clear();
  PyValue reply;
  std::vector<PyValue> args{PyValue::Str(LocalMachineId()),
                            PyValue::Bytes(object_id)};
  if (!Rpc("object_shm_ref", args, &reply, error)) return false;
  if (reply.kind != PyValue::Kind::kStr || reply.s.empty()) {
    return false;  // no same-machine copy: caller falls back to Get
  }
  const std::string arena_path = reply.s + "/arena";
  void* handle = nullptr;
  {
    static std::mutex arenas_mu;
    static std::map<std::string, void*> arenas;  // attach once per arena
    std::lock_guard<std::mutex> g(arenas_mu);
    auto it = arenas.find(arena_path);
    if (it != arenas.end()) {
      handle = it->second;
    } else {
      handle = rt_store_open(arena_path.c_str(), 0, 0, /*create=*/0);
      if (handle) arenas[arena_path] = handle;
    }
  }
  if (handle) {
    uint64_t size = 0;
    uint64_t off = rt_store_get(
        handle, reinterpret_cast<const uint8_t*>(object_id.data()), &size);
    if (off) {
      const char* base = static_cast<const char*>(rt_store_base(handle));
      blob->assign(base + off, size);  // pinned for exactly this copy
      rt_store_release(handle,
                       reinterpret_cast<const uint8_t*>(object_id.data()));
      return true;
    }
  }
  // not in the arena: objects too large for it (or arena-full puts) live in
  // the file-per-object fallback as <shm_dir>/<hex>.obj — 8-byte LE size,
  // payload at offset 16 (mirrors read_peer_pinned, object_transfer.py)
  static const char* kHex = "0123456789abcdef";
  std::string hex;
  hex.reserve(object_id.size() * 2);
  for (unsigned char c : object_id) {
    hex += kHex[c >> 4];
    hex += kHex[c & 15];
  }
  const std::string obj_path = reply.s + "/" + hex + ".obj";
  FILE* f = fopen(obj_path.c_str(), "rb");
  if (!f) return false;  // evicted/spilled since the location answer
  uint8_t hdr[16];
  if (fread(hdr, 1, 16, f) != 16) {
    fclose(f);
    return false;
  }
  uint64_t fsize = 0;
  for (int i = 0; i < 8; i++) fsize |= uint64_t(hdr[i]) << (8 * i);
  blob->resize(fsize);
  size_t got = fsize ? fread(&(*blob)[0], 1, fsize, f) : 0;
  fclose(f);
  if (got != fsize) {
    blob->clear();
    return false;
  }
  return true;
}

bool Client::GetLocal(const std::string& object_id, PyValue* out,
                      std::string* error) {
  std::string blob;
  if (!GetLocalShm(object_id, &blob, error)) return false;
  return DecodeFrame(blob, out, error);
}

bool Client::CallActor(const std::string& name, const std::string& method,
                       const std::vector<PyValue>& args,
                       std::string* object_id, std::string* error,
                       const std::string& ns) {
  Pickler ap;
  ap.Mark();
  for (const auto& a : args) ap.Value(a);
  ap.Tuple();
  std::string args_blob = ap.Finish();
  PyValue reply;
  std::vector<PyValue> rpc_args{PyValue::Str(ns), PyValue::Str(name),
                                PyValue::Str(method),
                                PyValue::Bytes(args_blob)};
  if (!Rpc("call_actor", rpc_args, &reply, error)) return false;
  if (reply.kind != PyValue::Kind::kBytes || reply.s.size() != 28) {
    *error = "call_actor returned unexpected value";
    return false;
  }
  *object_id = reply.s;
  return true;
}

}  // namespace ray_tpu
