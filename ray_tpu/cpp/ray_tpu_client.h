// C++ API frontend for the ray_tpu cluster.
//
// Parity role: the reference's C++ user API (`cpp/include/ray/api/*.h`,
// `cpp/src/ray/runtime/`) — a third-language client of the cluster core.
// This client speaks the head's native socket protocol directly
// (multiprocessing.connection framing + HMAC-SHA256 challenge auth +
// a pickled-tuple message encoding), registering as a remote driver the way
// `ray_tpu.init(address=...)` does (`ray_tpu/_private/client.py`).
//
// Supported surface: cluster introspection, object put/get (bytes and
// primitive values), named-actor method invocation (the `call_actor` RPC).
// Task submission with C++ function payloads would require C++ workers and is
// out of scope (the reference ships a full C++ worker runtime for that).

#ifndef RAY_TPU_CPP_CLIENT_H_
#define RAY_TPU_CPP_CLIENT_H_

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace ray_tpu {

// A tagged union for the subset of Python values the wire protocol carries.
struct PyValue {
  enum class Kind { kNone, kBool, kInt, kFloat, kStr, kBytes, kTuple, kList,
                    kDict, kObject };
  Kind kind = Kind::kNone;
  bool b = false;
  int64_t i = 0;
  double f = 0.0;
  std::string s;                       // kStr / kBytes payload
  std::vector<PyValue> items;          // kTuple / kList
  std::vector<std::pair<PyValue, PyValue>> dict;  // kDict
  std::string repr;                    // kObject: "module.Name(...)" summary

  static PyValue None();
  static PyValue Bool(bool v);
  static PyValue Int(int64_t v);
  static PyValue Float(double v);
  static PyValue Str(std::string v);
  static PyValue Bytes(std::string v);
  const PyValue* DictGet(const std::string& key) const;
};

class Client {
 public:
  Client();
  ~Client();
  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  // Connect + authenticate + register as a remote driver.
  bool Connect(const std::string& host, int port, const std::string& auth_key,
               std::string* error);
  void Close();
  bool connected() const;

  // Aggregate {resource: total} over alive nodes (rpc "list_nodes").
  bool ClusterResources(std::map<std::string, double>* out, std::string* error);

  // Store a value in the cluster object store; returns the 28-byte object id.
  bool Put(const PyValue& value, std::string* object_id, std::string* error);

  // Fetch an object committed in the cluster (polls rpc "get_object_blob").
  bool Get(const std::string& object_id, double timeout_s, PyValue* out,
           std::string* error);

  // Zero-copy local data plane (parity role: plasma client mmap access):
  // when this process runs on the SAME MACHINE as a node holding the
  // object, read the serialized blob straight out of that node's shm
  // arena — this client links the node's own C++ store (rt_store.h), so
  // the read is one memcpy from mapped memory, no socket, no head relay.
  // Returns false with an empty *error when no same-machine sealed copy
  // exists (callers fall back to Get).
  bool GetLocalShm(const std::string& object_id, std::string* blob,
                   std::string* error);

  // GetLocalShm + flat-frame decode (the store's <IQ> header + pickle +
  // 64-byte-aligned raw buffers). Values without out-of-band buffers
  // decode fully; buffer-carrying values (numpy) are rejected like Get.
  bool GetLocal(const std::string& object_id, PyValue* out,
                std::string* error);

  // Invoke `method` on the actor registered under `name`; returns the result
  // object id (fetch it with Get).
  bool CallActor(const std::string& name, const std::string& method,
                 const std::vector<PyValue>& args, std::string* object_id,
                 std::string* error,
                 const std::string& ns = "default");

  // Raw RPC escape hatch: op + already-pickled args tuple.
  bool Rpc(const std::string& op, const std::vector<PyValue>& args,
           PyValue* result, std::string* error);

 private:
  struct Impl;
  std::unique_ptr<Impl> impl_;
};

}  // namespace ray_tpu

#endif  // RAY_TPU_CPP_CLIENT_H_
