"""Training step-time & goodput attribution plane — "where did the step go".

The PR-11 tracing plane answers "where did the time go" per *request* and
the memory plane answers "where did the bytes go" per *object*; this module
answers the same question for the workload the north star optimizes:
distributed JAX training steps. Every ``train.report`` boundary closes one
**step record** per rank, decomposing wall step time into

    data_wait (batch-iterator blocking, with per-operator stall attribution
               from the streaming executor's backpressure state)
    -> host_to_device (device_put in iter_jax_batches)
    -> compile (jax.monitoring duration events, attributed to the step that
                triggered them; a recompilation detector flags steps that
                compile after warmup, with the changed batch shape signature)
    -> compute (the residual of the loop half of the step)
    -> collective_wait (head-side: cross-rank skew of the pre-report
                        timestamps, naming the straggler rank)
    -> checkpoint_stall (the blocking local-snapshot portion of
                         train.report(checkpoint=), joining the PR-5
                         checkpoint_save spans)
    -> other (honest residue: report/collector overhead and anything the
              seams above did not measure)

Worker side: a :class:`StepTimer` per training session, activated
process-wide so the data iterator and the jax monitoring listener can
publish into the active step without plumbing. Each finalized record RIDES
THE NEXT ``train.report`` collector rpc (zero extra messages on the step
hot path — the memory plane's ride-existing-messages rule; the session's
last record and any driver-local sessions drain through the PR-2 telemetry
ring instead), is drained by the executor, and lands batched (publish
cadence) in the scheduler's bounded per-run :class:`StepIndex`, which
computes the cross-rank skew once every rank's record for a step has
landed and keeps run-level stage aggregates for evicted steps.

Head side the :class:`StepIndex` also merges executor-pushed run metadata
(the ``train_run_meta`` rpc): live goodput and the **downtime ledger** —
goodput upgraded from one end-of-run scalar into windows attributed by
cause (recovery, gang_restart, preemption, checkpoint_drain,
admission_wait) so a chaos run's goodput loss sums to its attributed
downtime.

Surfaces: ``ray_tpu.train_timeline(run)``, ``state.list_train_runs()`` /
``state.train_run(run)``, the ``ray_tpu train`` CLI, the dashboard train
tab, and the ``ray_tpu_train_*`` Prometheus series below.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# enabled gate (memoized per runtime, like memplane)
# ---------------------------------------------------------------------------

_enabled_cache: Tuple[Optional[object], bool] = (None, False)


def enabled() -> bool:
    """Plane on? ``train_obs_enabled`` config flag; requires the telemetry
    pipeline (records ride its batches). Unconnected processes read as
    disabled."""
    global _enabled_cache
    try:
        from ray_tpu._private import worker as worker_mod

        rt = worker_mod._worker_runtime or worker_mod._driver
        if rt is None:
            return False
        cached_rt, val = _enabled_cache
        if rt is cached_rt:
            return val
        cfg = getattr(rt, "config", None)
        val = bool(getattr(cfg, "train_obs_enabled", True)) and bool(
            getattr(cfg, "telemetry_enabled", True)
        )
        _enabled_cache = (rt, val)
        return val
    except Exception:
        return False


def _config_attr(name: str, default):
    try:
        from ray_tpu._private import worker as worker_mod

        rt = worker_mod._worker_runtime or worker_mod._driver
        cfg = getattr(rt, "config", None)
        v = getattr(cfg, name, None)
        return default if v is None else v
    except Exception:
        return default


# ---------------------------------------------------------------------------
# worker-side metrics (single registration site per series — lint-enforced)
# ---------------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: Optional[Dict[str, Any]] = None


def _get_metrics() -> Dict[str, Any]:
    global _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram

            _metrics = {
                "step_stage": Histogram(
                    "ray_tpu_train_step_seconds",
                    "per-step stage decomposition of training steps "
                    "(seconds; stage=data_wait|host_to_device|compile|"
                    "compute|collective_wait|checkpoint_stall|other)",
                    tag_keys=("stage",),
                ),
                "step_wall": Histogram(
                    "ray_tpu_train_step_wall_seconds",
                    "whole-step wall time per rank (report boundary to "
                    "report boundary)",
                    tag_keys=("run",),
                ),
                "data_wait_ratio": Gauge(
                    "ray_tpu_train_data_wait_ratio",
                    "fraction of recent step wall spent blocked on the "
                    "batch iterator (input-bound indicator, per run)",
                    tag_keys=("run",),
                ),
                "recompiles": Counter(
                    "ray_tpu_train_recompiles_total",
                    "steps that triggered a jax recompilation AFTER the "
                    "warmup window (train_recompile_warmup_steps) — each "
                    "carries the changed batch shape signature",
                    tag_keys=("run",),
                ),
                "ingest_stall": Counter(
                    "ray_tpu_train_ingest_stall_seconds_total",
                    "batch-iterator blocking time attributed to the "
                    "bottleneck streaming-executor operator",
                    tag_keys=("run", "operator"),
                ),
                "compile_s": Counter(
                    "ray_tpu_train_compile_seconds_total",
                    "jax compile time attributed to training steps",
                    tag_keys=("run",),
                ),
                "h2d_s": Counter(
                    "ray_tpu_train_host_to_device_seconds_total",
                    "host->device batch transfer time (device_put in "
                    "iter_jax_batches)",
                    tag_keys=("run",),
                ),
                "ckpt_stall_s": Counter(
                    "ray_tpu_train_checkpoint_stall_seconds_total",
                    "blocking (local-snapshot) portion of "
                    "train.report(checkpoint=) — the async upload rides "
                    "the checkpoint plane",
                    tag_keys=("run",),
                ),
                "steps": Counter(
                    "ray_tpu_train_steps_total",
                    "training steps completed (one per rank per step)",
                    tag_keys=("run",),
                ),
            }
    return _metrics


# ---------------------------------------------------------------------------
# the active timer (thread-local with a process-wide fallback, mirroring
# _session._set_session: the SIGTERM drain and the jax monitoring listener
# can fire on side threads of a worker running one session)
# ---------------------------------------------------------------------------

_local = threading.local()
_timer_fallback: Optional["StepTimer"] = None


def activate(timer: Optional["StepTimer"]) -> None:
    global _timer_fallback
    prev = current()
    if prev is not None and prev is not timer:
        # session ending / being replaced: push its pending metric batch
        # and the last step's record (which has no next report to ride)
        try:
            prev.flush_metrics()
            prev.flush_pending_record()
        except Exception:
            pass
    _local.timer = timer
    _timer_fallback = timer


def current() -> Optional["StepTimer"]:
    t = getattr(_local, "timer", None)
    return t if t is not None else _timer_fallback


def note_data_wait(seconds: float, operator: Optional[str] = None) -> None:
    """Batch iterator blocked for ``seconds`` (data/iterator.py seam)."""
    t = current()
    if t is not None:
        t.note_data_wait(seconds, operator)


def note_host_to_device(seconds: float) -> None:
    t = current()
    if t is not None:
        t.note_host_to_device(seconds)


def note_compile(event: str, seconds: float) -> None:
    """One jax.monitoring duration event landed on this process (sampler
    listener seam); attributed to the active step if a timer is live."""
    t = current()
    if t is not None:
        t.note_compile(event, seconds)


def note_checkpoint_stall(seconds: float) -> None:
    t = current()
    if t is not None:
        t.note_checkpoint_stall(seconds)


def note_batch_signature(sig: str) -> None:
    t = current()
    if t is not None:
        t.note_batch_signature(sig)


def batch_signature(batch: Dict[str, Any]) -> str:
    """Abstract-shape signature of one batch dict — what jit retraces on.
    ``key:dtype[shape]`` per column, sorted for stability."""
    parts = []
    for k in sorted(batch):
        v = batch[k]
        shape = tuple(getattr(v, "shape", ()) or ())
        dtype = getattr(getattr(v, "dtype", None), "name", None) or type(v).__name__
        parts.append(f"{k}:{dtype}{list(shape)}")
    return ",".join(parts)


# ---------------------------------------------------------------------------
# worker-side per-step timer
# ---------------------------------------------------------------------------

# compile sub-phases are disjoint (trace -> mlir -> backend compile), so
# summing their durations is the compiled-time total; only the backend
# compile marks "a new executable was built" for the recompile detector
_RECOMPILE_EVENTS = ("backend_compile", "compile_time")


class StepTimer:
    """Accumulates one rank's stage times between ``train.report`` calls.

    Lifecycle per step: the loop half (data_wait / host_to_device /
    compile / compute) runs from the previous report's return (``t0``) to
    the next report's entry (``t1``, :meth:`mark_pre_report`); the report
    half (checkpoint_stall + collector overhead -> other) runs ``t1..t2``
    (:meth:`finalize_step`). ``compute`` is the loop residual; ``other``
    the report residual — both floored at zero so overlap (e.g. a compile
    inside a data-wait window) can only oversum, never hide time.
    """

    def __init__(self, run: str, rank: int, world: int,
                 warmup: Optional[int] = None):
        self.run = run
        self.rank = int(rank)
        self.world = int(world)
        self.warmup = int(
            warmup
            if warmup is not None
            else _config_attr("train_recompile_warmup_steps", 2)
        )
        self.steps_done = 0  # session-local (fresh process = cold jit cache)
        self._sig: Optional[str] = None
        self._sig_prev: Optional[str] = None
        self._last_flagged_sig: Optional[str] = None
        # locally-accumulated metric observations, flushed on a ~1s
        # cadence (per-step Histogram.observe calls each pay a snapshot
        # copy — 8 of them per step dominated the plane's overhead)
        self._pend_stage: Dict[str, List[float]] = {}
        self._pend_wall: List[float] = []
        self._pend_counts: Dict[str, float] = {}
        self._pend_ops: Dict[str, float] = {}
        self._pend_recompiles = 0
        self._last_ratio: Optional[float] = None
        self._last_metrics_flush = time.perf_counter()
        # the finalized-but-unshipped record awaiting the next report rpc
        self._pending_rec: Optional[tuple] = None
        # sub-floor steps coalesce here (stage sums + count) and emerge as
        # ONE merged record per flush interval — per-step rows for sub-ms
        # loops cost record construction per step and flood the bounded
        # step window without adding signal
        self._floor_ms = float(_config_attr("train_obs_min_step_ms", 2.0))
        self._co: Optional[list] = None  # [t0w, t1w, t2w, step, count,
        #                                  wall, dw, h2d, comp, cu, ck, ot,
        #                                  compile_events]
        # resolved once: per-step getattr/import walks (sampler probe,
        # telemetry buffer, enabled gate) priced out of finalize_step
        self._enabled = enabled()
        if self._enabled:
            from ray_tpu._private import telemetry

            self._buffer = telemetry.get_buffer()
            self._buffer.ensure_flusher()
        else:
            self._buffer = None
        try:
            from ray_tpu._private import sampler

            self._jax_probe = sampler.maybe_install_jax_hooks
            self._jax_probe_done = lambda: sampler._jax_hooked
        except Exception:
            self._jax_probe = lambda: None
            self._jax_probe_done = lambda: True
        self._hooks_done = False
        self._probe_jax_hooks()
        self._reset(time.time(), time.perf_counter())

    def _probe_jax_hooks(self) -> None:
        """The compile stage needs the jax.monitoring listener installed
        BEFORE the first post-warmup step — the telemetry flusher's 1s
        probe cadence could miss early compiles, so the timer probes too
        (cheap sys.modules check, never imports jax; stops re-probing
        once the hooks are in)."""
        if self._hooks_done:
            return
        try:
            self._jax_probe()
            self._hooks_done = self._jax_probe_done()
        except Exception:
            pass

    def _reset(self, wall_now: float, perf_now: float) -> None:
        self._t0_wall = wall_now
        self._t0 = perf_now
        self._t1_wall: Optional[float] = None
        self._t1: Optional[float] = None
        self._data_wait = 0.0
        self._h2d = 0.0
        self._compile = 0.0
        self._ckpt_stall = 0.0
        self._ops: Dict[str, float] = {}
        self._compile_events = 0
        self._recompiled = False

    # -- accumulation (loop-thread hot path, no locks: one session per
    # worker and GIL-atomic float adds) ------------------------------------

    def note_data_wait(self, seconds: float, operator: Optional[str]) -> None:
        s = max(0.0, float(seconds))
        self._data_wait += s
        if operator:
            self._ops[operator] = self._ops.get(operator, 0.0) + s

    def note_host_to_device(self, seconds: float) -> None:
        self._h2d += max(0.0, float(seconds))

    def note_compile(self, event: str, seconds: float) -> None:
        self._compile += max(0.0, float(seconds))
        tail = event.rstrip("/").rsplit("/", 1)[-1]
        if any(tail.startswith(e) for e in _RECOMPILE_EVENTS):
            self._compile_events += 1
            if self.steps_done >= self.warmup:
                self._recompiled = True

    def note_checkpoint_stall(self, seconds: float) -> None:
        self._ckpt_stall += max(0.0, float(seconds))

    def note_batch_signature(self, sig: str) -> None:
        if sig != self._sig:
            self._sig_prev, self._sig = self._sig, sig

    def mark_pre_report(self) -> None:
        """Entry of train.report: the loop half of the step ends here."""
        self._t1_wall = time.time()
        self._t1 = time.perf_counter()

    # -- finalize ----------------------------------------------------------

    def finalize_step(self, step: int, trace_id: Optional[str] = None) -> Optional[dict]:
        """Close the step at the report boundary; emit the record + metrics.
        Returns the record (None when the plane is disabled)."""
        end_wall = time.time()
        end = time.perf_counter()
        self._probe_jax_hooks()  # user code may import jax mid-run
        if self._t1 is None:  # report entry not marked (direct callers)
            self._t1, self._t1_wall = end, end_wall
        wall = max(0.0, end - self._t0)
        loop_wall = max(0.0, self._t1 - self._t0)
        report_wall = max(0.0, end - self._t1)
        compute = max(
            0.0, loop_wall - self._data_wait - self._h2d - self._compile
        )
        other = max(0.0, report_wall - self._ckpt_stall)
        wall_ms = wall * 1e3
        if (
            wall_ms < self._floor_ms
            and not self._recompiled
            and not self._ops
            and self._ckpt_stall == 0.0
        ):
            # sub-floor step: fold into the coalesced accumulator (exact
            # stage sums, no record build); materialized by _pop_coalesced
            # on the flush cadence / at session end
            co = self._co
            if co is None:
                co = self._co = [
                    self._t0_wall, self._t1_wall, end_wall, int(step), 0,
                    0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0,
                ]
            co[1] = self._t1_wall
            co[2] = end_wall
            co[3] = int(step)
            co[4] += 1
            co[5] += wall_ms
            co[6] += self._data_wait
            co[7] += self._h2d
            co[8] += self._compile
            co[9] += compute
            co[11] += other
            co[12] += self._compile_events
            rec = None
        else:
            # compact positional tuple (decode_record is the schema): a
            # dict per step measurably taxed the report hot path in build
            # AND batch-pickle cost — the memory plane's tuple trick
            rec = (
                self.run,
                self.rank,
                self.world,
                int(step),
                self._t0_wall,
                self._t1_wall,
                end_wall,
                wall_ms,
                (
                    self._data_wait * 1e3,
                    self._h2d * 1e3,
                    self._compile * 1e3,
                    compute * 1e3,
                    self._ckpt_stall * 1e3,
                    other * 1e3,
                ),
                {k: v * 1e3 for k, v in self._ops.items()}
                if self._ops
                else None,
                trace_id,
                self._compile_events,
                1 if self._recompiled else 0,
                self._sig,
                1,
            )
        recompiled = self._recompiled
        sig, sig_prev = self._sig, self._sig_prev
        ops = dict(self._ops)
        data_wait, h2d, compile_s, ckpt = (
            self._data_wait, self._h2d, self._compile, self._ckpt_stall,
        )
        self.steps_done += 1
        self._reset(end_wall, end)
        if not self._enabled:
            return None
        # the record RIDES THE NEXT REPORT's collector rpc (zero extra
        # messages on the step hot path — the memory plane's trick): it
        # parks here until pop_pending_record() attaches it, and the
        # session's LAST record drains through the telemetry ring when
        # the timer deactivates (flush_pending_record)
        if rec is not None:
            prev = self._pending_rec
            if prev is not None and self._buffer is not None:
                # collector-less session (driver-local loops): nothing
                # pops the slot — ship the displaced record via telemetry
                self._buffer.record_train_step(prev)
            self._pending_rec = rec
        # accumulate metric observations locally; flush on a cadence
        for stage, v in (
            ("data_wait", data_wait),
            ("host_to_device", h2d),
            ("compile", compile_s),
            ("compute", compute),
            ("checkpoint_stall", ckpt),
            ("other", other),
        ):
            if v > 0 or stage == "compute":
                self._pend_stage.setdefault(stage, []).append(v)
        self._pend_wall.append(wall)
        self._pend_counts["steps"] = self._pend_counts.get("steps", 0) + 1
        if wall > 0:
            self._last_ratio = data_wait / wall  # rounded at flush
        for key, v in (("compile_s", compile_s), ("h2d_s", h2d),
                       ("ckpt_stall_s", ckpt)):
            if v:
                self._pend_counts[key] = self._pend_counts.get(key, 0.0) + v
        for op, v in ops.items():
            self._pend_ops[op] = self._pend_ops.get(op, 0.0) + v
        if recompiled:
            self._pend_recompiles += 1
        if end - self._last_metrics_flush >= 1.0:
            self.flush_metrics(end)
        if recompiled and sig != self._last_flagged_sig:
            # one WARNING per changed signature, not per step: a shape
            # bug recompiling EVERY step would otherwise flood the
            # bounded event log
            self._last_flagged_sig = sig
            try:
                from ray_tpu._private import telemetry

                telemetry.record_cluster_event(
                    "TRAIN_RECOMPILE",
                    f"run {self.run} rank {self.rank}: step {step} "
                    f"recompiled after warmup ({self.warmup} steps) — "
                    f"batch signature changed "
                    f"{sig_prev or '<unknown>'} -> {sig or '<unknown>'}",
                    severity="WARNING",
                    source="TRAIN",
                    run=self.run,
                    rank=self.rank,
                    step=int(step),
                    signature=sig,
                    previous_signature=sig_prev,
                )
            except Exception:
                pass
        return rec

    def pop_pending_record(self):
        """The previous step's finalized record, to attach to the next
        report rpc (None when none pending or the plane is off)."""
        rec, self._pending_rec = self._pending_rec, None
        return rec

    def _emit_coalesced(self) -> None:
        """Materialize the coalesced sub-floor block as one merged record
        (flush cadence / session end): parks in the pending slot when
        free, else ships via the telemetry ring (both cold paths)."""
        co, self._co = self._co, None
        if co is None or not co[4]:
            return
        t0w, t1w, t2w, step, count, wall, dw, h2d, comp, cu, ck, ot, cev = co
        rec = (
            self.run, self.rank, self.world, step, t0w, t1w, t2w, wall,
            (dw * 1e3, h2d * 1e3, comp * 1e3, cu * 1e3, ck * 1e3, ot * 1e3),
            None, None, cev, 0, self._sig, count,
        )
        if self._pending_rec is None:
            self._pending_rec = rec
        elif self._buffer is not None:
            self._buffer.record_train_step(rec)

    def flush_pending_record(self) -> None:
        """Session ending: the last step's record (and any coalesced
        block) has no next report to ride — ship via the telemetry ring
        (cold path)."""
        self._emit_coalesced()
        rec = self.pop_pending_record()
        if rec is not None and self._buffer is not None:
            self._buffer.record_train_step(rec)
            self._buffer.ensure_flusher()

    def flush_metrics(self, now: Optional[float] = None) -> None:
        """Emit the locally-accumulated observations (batched: one
        snapshot copy per series per flush, not per step). Called on the
        ~1s cadence from finalize_step and when the session deactivates."""
        self._last_metrics_flush = (
            now if now is not None else time.perf_counter()
        )
        self._emit_coalesced()
        if not self._pend_wall and not self._pend_counts:
            return
        if self._buffer is not None:
            self._buffer.ensure_flusher()
        try:
            m = _get_metrics()
            run_tag = {"run": self.run}
            for stage, vals in self._pend_stage.items():
                m["step_stage"].observe_many(vals, tags={"stage": stage})
            m["step_wall"].observe_many(self._pend_wall, tags=run_tag)
            if self._last_ratio is not None:
                m["data_wait_ratio"].set(
                    round(self._last_ratio, 4), tags=run_tag
                )
            counts = self._pend_counts
            if counts.get("steps"):
                m["steps"].inc(counts["steps"], tags=run_tag)
            for key in ("compile_s", "h2d_s", "ckpt_stall_s"):
                if counts.get(key):
                    m[key].inc(counts[key], tags=run_tag)
            for op, v in self._pend_ops.items():
                m["ingest_stall"].inc(
                    v, tags={"run": self.run, "operator": op}
                )
            if self._pend_recompiles:
                m["recompiles"].inc(self._pend_recompiles, tags=run_tag)
        except Exception:
            pass
        self._pend_stage = {}
        self._pend_wall = []
        self._pend_counts = {}
        self._pend_ops = {}
        self._pend_recompiles = 0


def make_timer(run: str, rank: int, world: int) -> Optional[StepTimer]:
    """A StepTimer when the plane is on, else None (callers keep a None
    check on their hot path instead of a disabled timer's overhead)."""
    return StepTimer(run, rank, world) if enabled() else None


# ---------------------------------------------------------------------------
# head-side per-run step index (lives in the scheduler)
# ---------------------------------------------------------------------------

_STAGE_KEYS = (
    "data_wait_ms",
    "host_to_device_ms",
    "compile_ms",
    "compute_ms",
    "collective_wait_ms",
    "checkpoint_stall_ms",
    "other_ms",
)

# positional order of the compact step-record tuple finalize_step emits
_REC_STAGE_KEYS = (
    "data_wait_ms",
    "host_to_device_ms",
    "compile_ms",
    "compute_ms",
    "checkpoint_stall_ms",
    "other_ms",
)


def decode_record(rec) -> Optional[dict]:
    """Compact step-record tuple -> the dict shape the StepIndex stores
    (None on malformed input — telemetry batches are untrusted). The
    trailing ``merged`` count is 1 for a real per-step row, >1 for a
    coalesced block of sub-floor steps (stage values are sums over it)."""
    try:
        (run, rank, world, step, t0, t1, t2, wall_ms, stages, ops,
         trace_id, compile_events, recompiled, sig, merged) = rec
        return {
            "merged": int(merged),
            "run": run,
            "rank": int(rank),
            "world": int(world),
            "step": int(step),
            "t0": t0,
            "t1": t1,
            "t2": t2,
            "wall_ms": round(float(wall_ms), 3),
            "stages": {
                k: round(float(v), 3)
                for k, v in zip(_REC_STAGE_KEYS, stages)
            },
            "ops": {k: round(float(v), 3) for k, v in (ops or {}).items()},
            "trace_id": trace_id,
            "compile_events": int(compile_events),
            "recompiled": bool(recompiled),
            "sig": sig,
        }
    except (TypeError, ValueError):
        return None


class StepIndex:
    """Bounded cluster-side index of train-step records + run metadata.

    One entry per run: a per-step ``{rank: record}`` table (bounded by
    ``train_step_index_max`` steps, oldest evicted into run-level stage
    aggregates so totals survive eviction) plus executor-pushed metadata
    (goodput, downtime ledger, status). The cross-rank ``collective_wait``
    stage and the straggler rank are computed here, once every rank's
    record for a step has landed, from the step-boundary timestamps: the
    rank with the longest step-local loop span is the straggler, and the
    other ranks' collectives waited the difference for it.
    """

    def __init__(self, config=None):
        self._cfg = config
        self._runs: "collections.OrderedDict[str, dict]" = collections.OrderedDict()
        self._lock = threading.Lock()

    def _max_steps(self) -> int:
        return int(getattr(self._cfg, "train_step_index_max", 512) or 512)

    def _max_runs(self) -> int:
        return int(getattr(self._cfg, "train_runs_max", 32) or 32)

    def _run_entry(self, run: str) -> dict:
        entry = self._runs.get(run)
        if entry is None:
            while len(self._runs) >= self._max_runs():
                self._runs.popitem(last=False)
            entry = self._runs[run] = {
                "run": run,
                "world": 0,
                "steps": collections.OrderedDict(),  # step -> {rank: rec}
                "totals": {k: 0.0 for k in _STAGE_KEYS},
                "wall_ms_total": 0.0,
                "records": 0,
                # per-rank cumulative step counts (merged blocks included);
                # the run's step count is the MAX over ranks — summing
                # first-arrivals would double-count coalesced blocks whose
                # unsynchronized flushes land on different step keys
                "rank_steps": {},
                "evicted_steps": 0,
                "recompiles": 0,
                "ops": {},
                "skew": {},  # step -> {skew_ms, straggler_rank}
                "max_skew_ms": 0.0,
                "first_time": None,
                "last_time": None,
                "meta": {},
            }
        return entry

    # -- ingest ------------------------------------------------------------

    def ingest(self, rec) -> None:
        if isinstance(rec, (tuple, list)):
            rec = decode_record(rec)
        if not rec:
            return
        run = rec.get("run")
        step = rec.get("step")
        if not run or step is None:
            return
        with self._lock:
            entry = self._run_entry(str(run))
            entry["world"] = max(entry["world"], int(rec.get("world") or 1))
            steps = entry["steps"]
            per_rank = steps.get(step)
            if per_rank is None:
                per_rank = steps[step] = {}
                while len(steps) > self._max_steps():
                    _old_step, old = steps.popitem(last=False)
                    entry["evicted_steps"] += 1
                    for r in old.values():
                        self._fold_totals(entry, r)
            rank = int(rec.get("rank") or 0)
            rs = entry["rank_steps"]
            rs[rank] = rs.get(rank, 0) + int(rec.get("merged") or 1)
            old = per_rank.get(rank)
            if old is not None:
                rs[rank] -= int(old.get("merged") or 1)
                # at-least-once delivery: the executor re-queues a batch
                # whose rpc failed after the scheduler applied it — back
                # out the superseded record's aggregate contributions so
                # re-ingest is idempotent
                self._fold_totals(entry, old, live=True, sign=-1.0)
                if old.get("recompiled"):
                    entry["recompiles"] -= 1
                for op, v in (old.get("ops") or {}).items():
                    entry["ops"][op] = entry["ops"].get(op, 0.0) - float(v)
            else:
                entry["records"] += 1
            per_rank[rank] = rec
            self._fold_totals(entry, rec, live=True)
            t = rec.get("t2") or rec.get("t0")
            if t:
                if entry["first_time"] is None:
                    entry["first_time"] = t
                entry["last_time"] = max(entry["last_time"] or 0.0, t)
            if rec.get("recompiled"):
                entry["recompiles"] += 1
            for op, v in (rec.get("ops") or {}).items():
                entry["ops"][op] = entry["ops"].get(op, 0.0) + float(v)
            if len(per_rank) >= int(rec.get("world") or 1):
                self._note_skew(entry, step, per_rank)

    def _fold_totals(
        self, entry: dict, rec: dict, live: bool = False, sign: float = 1.0
    ) -> None:
        """Run-level stage totals. Live records fold immediately (wall +
        stages; ``sign=-1`` backs a superseded duplicate out); eviction
        folds only what ingest could not know then — nothing, so evicted
        records are a no-op beyond the counter. Kept as one seam so a
        future late-computed stage folds here."""
        if not live:
            return
        for k, v in (rec.get("stages") or {}).items():
            if k in entry["totals"]:
                entry["totals"][k] += sign * float(v or 0.0)
        entry["wall_ms_total"] += sign * float(rec.get("wall_ms") or 0.0)

    def _note_skew(self, entry: dict, step, per_rank: Dict[int, dict]) -> None:
        """All ranks reported this step: attribute cross-rank skew from
        the step-boundary timestamps. The skew is STEP-LOCAL — each
        rank's loop span (step start ``t0`` to pre-report ``t1``) against
        the longest rank's — so drift a rank carried INTO the step (free-
        running loops with no collectives pull apart across steps; a raw
        ``t1_max - t1`` would relabel whole steps) never compounds. The
        rank with the longest loop span is the straggler; every other
        rank's collectives waited the difference for it, time that was
        sitting inside its measured compute residual — move it, capped at
        that residual so the per-rank stage sum stays an invariant."""
        loops = {}
        for r, rec in per_rank.items():
            t0, t1 = rec.get("t0"), rec.get("t1")
            if t0 is not None and t1 is not None:
                loops[r] = max(0.0, (t1 - t0) * 1e3)
        if len(loops) < 2:
            return
        loop_max = max(loops.values())
        straggler = max(loops, key=lambda r: loops[r])
        skew_ms = 0.0
        for r, rec in per_rank.items():
            loop_ms = loops.get(r)
            if loop_ms is None:
                continue
            stages = rec.setdefault("stages", {})
            prev = float(stages.get("collective_wait_ms") or 0.0)
            pool = float(stages.get("compute_ms") or 0.0) + prev
            wait_ms = min(max(0.0, loop_max - loop_ms), pool)
            skew_ms = max(skew_ms, wait_ms)
            stages["collective_wait_ms"] = round(wait_ms, 3)
            stages["compute_ms"] = round(max(0.0, pool - wait_ms), 3)
            entry["totals"]["collective_wait_ms"] += wait_ms - prev
            entry["totals"]["compute_ms"] -= min(
                wait_ms - prev, entry["totals"]["compute_ms"]
            )
            rec["straggler"] = r == straggler
        entry["skew"][step] = {
            "skew_ms": round(skew_ms, 3),
            "straggler_rank": straggler,
        }
        entry["max_skew_ms"] = max(entry["max_skew_ms"], skew_ms)
        # bounded alongside the step table
        while len(entry["skew"]) > self._max_steps():
            entry["skew"].pop(next(iter(entry["skew"])), None)
        try:
            from ray_tpu.util.metrics import Gauge, Histogram

            global _head_metrics
            if _head_metrics is None:
                _head_metrics = {
                    "skew": Histogram(
                        "ray_tpu_train_rank_skew_seconds",
                        "cross-rank step-boundary skew (time the earliest "
                        "rank's collectives waited for the straggler rank)",
                        tag_keys=("run",),
                    ),
                    "straggler": Gauge(
                        "ray_tpu_train_straggler_rank",
                        "rank whose pre-report timestamp was latest on the "
                        "most recent fully-reported step (the rank the "
                        "others waited on; joinable with the STRAGGLER "
                        "watchdog events)",
                        tag_keys=("run",),
                    ),
                }
            _head_metrics["skew"].observe(
                skew_ms / 1e3, tags={"run": entry["run"]}
            )
            _head_metrics["straggler"].set(
                straggler, tags={"run": entry["run"]}
            )
        except Exception:
            pass

    def note_meta(self, run: str, meta: dict) -> None:
        """Merge executor-pushed run metadata (goodput stats, downtime
        ledger, world size, status) — the ``train_run_meta`` rpc."""
        if not run:
            return
        with self._lock:
            entry = self._run_entry(str(run))
            entry["meta"].update(meta or {})
            if meta and meta.get("world_size"):
                entry["world"] = max(entry["world"], int(meta["world_size"]))

    # -- reads -------------------------------------------------------------

    def list_runs(self) -> List[dict]:
        with self._lock:
            out = []
            for entry in self._runs.values():
                meta = entry["meta"]
                gp = meta.get("goodput") or {}
                out.append(
                    {
                        "run": entry["run"],
                        "world": entry["world"],
                        "steps": self._steps_seen(entry),
                        "records": entry["records"],
                        "recompiles": entry["recompiles"],
                        "goodput": gp.get("goodput"),
                        "downtime_s": round(
                            sum(
                                e.get("seconds", 0.0)
                                for e in meta.get("downtime_ledger") or ()
                            ),
                            3,
                        ),
                        "status": meta.get("status", "running"),
                        "data_wait_ratio": self._ratio(entry, "data_wait_ms"),
                        "max_skew_ms": round(entry["max_skew_ms"], 3),
                        "first_time": entry["first_time"],
                        "last_time": entry["last_time"],
                    }
                )
            return list(reversed(out))  # newest-registered first

    @staticmethod
    def _steps_seen(entry: dict) -> int:
        return max(entry["rank_steps"].values(), default=0)

    @staticmethod
    def _ratio(entry: dict, stage: str) -> Optional[float]:
        wall = entry["wall_ms_total"]
        if not wall:
            return None
        return round(entry["totals"].get(stage, 0.0) / wall, 4)

    def get_run(self, run: str, max_steps: Optional[int] = None) -> Optional[dict]:
        with self._lock:
            entry = self._runs.get(str(run))
            if entry is None:
                return None
            steps_items = list(entry["steps"].items())
            if max_steps:
                steps_items = steps_items[-int(max_steps):]
            return {
                "run": entry["run"],
                "world": entry["world"],
                "steps_seen": self._steps_seen(entry),
                "rank_steps": {
                    str(r): n for r, n in entry["rank_steps"].items()
                },
                "evicted_steps": entry["evicted_steps"],
                "records": entry["records"],
                "recompiles": entry["recompiles"],
                "totals": {k: round(v, 3) for k, v in entry["totals"].items()},
                "wall_ms_total": round(entry["wall_ms_total"], 3),
                "ops": {k: round(v, 3) for k, v in entry["ops"].items()},
                "skew": dict(entry["skew"]),
                "max_skew_ms": round(entry["max_skew_ms"], 3),
                "first_time": entry["first_time"],
                "last_time": entry["last_time"],
                "meta": dict(entry["meta"]),
                "steps": [
                    {
                        "step": step,
                        "ranks": {
                            str(r): dict(rec) for r, rec in per_rank.items()
                        },
                    }
                    for step, per_rank in steps_items
                ],
            }


_head_metrics: Optional[Dict[str, Any]] = None


# ---------------------------------------------------------------------------
# timeline view (ray_tpu.train_timeline / CLI rendering)
# ---------------------------------------------------------------------------

_BAR_CHARS = {
    "data_wait_ms": "d",
    "host_to_device_ms": "h",
    "compile_ms": "J",
    "compute_ms": "#",
    "collective_wait_ms": "w",
    "checkpoint_stall_ms": "c",
    "other_ms": ".",
}


class TrainTimeline:
    """One run's step-time attribution, renderable as a per-rank step
    waterfall (``summary()``) or consumed as a dict (``to_dict()``)."""

    def __init__(self, data: dict):
        self.data = data or {}

    @property
    def run(self) -> str:
        return self.data.get("run", "?")

    def to_dict(self) -> dict:
        return dict(self.data)

    def step_count(self) -> int:
        return int(self.data.get("steps_seen") or 0)

    def stage_shares(self) -> Dict[str, float]:
        """Stage -> fraction of total recorded step wall (all ranks)."""
        wall = float(self.data.get("wall_ms_total") or 0.0)
        if not wall:
            return {}
        return {
            k.replace("_ms", ""): round(v / wall, 4)
            for k, v in (self.data.get("totals") or {}).items()
        }

    @staticmethod
    def _bar(stages: Dict[str, float], wall_ms: float, width: int = 28) -> str:
        if wall_ms <= 0:
            return " " * width
        out = []
        for key in _STAGE_KEYS:
            n = int(round(width * float(stages.get(key) or 0.0) / wall_ms))
            out.append(_BAR_CHARS[key] * n)
        bar = "".join(out)[:width]
        return bar + " " * (width - len(bar))

    def summary(self, max_steps: int = 20) -> str:
        d = self.data
        if not d:
            return "no step records for this run"
        meta = d.get("meta") or {}
        gp = meta.get("goodput") or {}
        out = [
            f"train run {d.get('run')}  world={d.get('world')}  "
            f"steps={d.get('steps_seen')}  recompiles={d.get('recompiles')}"
            + (
                f"  goodput={gp['goodput']:.3f}"
                if gp.get("goodput") is not None
                else ""
            )
        ]
        shares = self.stage_shares()
        if shares:
            out.append(
                "stage shares: "
                + "  ".join(
                    f"{k}={v * 100:.1f}%"
                    for k, v in shares.items()
                    if v >= 0.0005
                )
            )
        ops = d.get("ops") or {}
        if ops:
            out.append(
                "ingest stalls by operator: "
                + "  ".join(
                    f"{op}={ms:.0f}ms"
                    for op, ms in sorted(ops.items(), key=lambda kv: -kv[1])
                )
            )
        ledger = meta.get("downtime_ledger") or []
        if ledger:
            total = sum(e.get("seconds", 0.0) for e in ledger)
            out.append(f"downtime ledger ({total:.2f}s attributed):")
            for e in ledger:
                out.append(
                    f"  {e.get('cause', '?'):<16} {e.get('seconds', 0.0):8.2f}s"
                    + (f"  {e['detail']}" if e.get("detail") else "")
                )
        steps = (d.get("steps") or [])[-max_steps:]
        if steps:
            legend = " ".join(
                f"{c}={k.replace('_ms', '')}" for k, c in _BAR_CHARS.items()
            )
            out.append(f"step waterfall (last {len(steps)}; {legend}):")
        for srec in steps:
            step = srec.get("step")
            skew = (d.get("skew") or {}).get(step) or {}
            for r in sorted(srec.get("ranks") or {}, key=int):
                rec = srec["ranks"][r]
                stages = rec.get("stages") or {}
                wall = float(rec.get("wall_ms") or 0.0)
                mark = (
                    " <- straggler"
                    if skew and int(r) == skew.get("straggler_rank")
                    and skew.get("skew_ms", 0) > 0
                    else ""
                )
                bd = "  ".join(
                    f"{k.replace('_ms', '')}={float(stages.get(k) or 0):.0f}"
                    for k in _STAGE_KEYS
                    if float(stages.get(k) or 0.0) >= 0.5
                )
                flag = " RECOMPILED" if rec.get("recompiled") else ""
                out.append(
                    f"  step {step:>5} rank {r} "
                    f"|{self._bar(stages, wall)}| {wall:8.1f}ms  "
                    f"[{bd}]{flag}{mark}"
                )
        return "\n".join(out)
